"""GCE / TPU-VM node provider: the real-cloud seam for the autoscaler.

Role analog: the reference's GCP provider + TPU support
(``python/ray/autoscaler/_private/gcp/node_provider.py:75-94``,
TPU pod resource fill-in ``:283-292``, REST client split compute/tpu in
``gcp/node.py``). Re-designed for this framework: instead of the
googleapiclient discovery stack, a single injectable ``transport``
callable carries every REST call, so the provider is fully unit-testable
against a recorded API surface and swaps to live HTTP (metadata-server
auth) on a real TPU VM.

TPU slices are first-class: ``create_slice`` provisions ONE TPU pod node
(`projects.locations.nodes.create`), waits for the operation, then maps
each ``networkEndpoint`` (one per host) to a NodeInfo carrying the
pod-slice resources of the accelerator layer (``accelerators/tpu.py``):
every host gets ``{"TPU": chips_per_host, "<slice-name>": 1}`` and host 0
additionally ``{"TPU-<type>-head": 1}`` so drivers can target the head
and fan out one task per host (reference ``tpu.py:335-398`` semantics).
"""

from __future__ import annotations

import json
import time
import urllib.request
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeInfo, NodeProvider

TPU_API = "https://tpu.googleapis.com/v2"
GCE_API = "https://compute.googleapis.com/compute/v1"
METADATA_TOKEN_URL = ("http://metadata.google.internal/computeMetadata/v1/"
                      "instance/service-accounts/default/token")

def _chips_per_host(accelerator_type: str) -> int:
    """Chips per host: 4 across generations (reference tpu.py:274-287 —
    v2-v4 are 4 dual-core chips per host, v5+ are 4 single-chip boards).
    The HOST COUNT itself always comes from the API's networkEndpoints,
    never from this arithmetic."""
    return 4


class LiveTransport:
    """Minimal authenticated REST transport (runs ON a GCP VM: token from
    the metadata server). Everything network-touching lives here so tests
    never need it."""

    def __init__(self):
        self._token: Optional[str] = None
        self._token_exp = 0.0

    def _auth(self) -> str:
        if self._token is None or time.time() > self._token_exp - 60:
            req = urllib.request.Request(
                METADATA_TOKEN_URL, headers={"Metadata-Flavor": "Google"})
            with urllib.request.urlopen(req, timeout=10) as r:
                tok = json.loads(r.read())
            self._token = tok["access_token"]
            self._token_exp = time.time() + float(tok.get("expires_in", 300))
        return self._token

    def __call__(self, method: str, url: str,
                 body: Optional[dict] = None) -> dict:
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            url, data=data, method=method,
            headers={"Authorization": f"Bearer {self._auth()}",
                     "Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            payload = r.read()
        return json.loads(payload) if payload else {}


class GcpTpuNodeProvider(NodeProvider):
    """Provisions TPU-VM slices + GCE CPU workers, labeled per cluster.

    ``node_types``: name -> spec dict. TPU specs carry
    ``{"kind": "tpu", "accelerator_type": "v5litepod-16",
    "runtime_version": "tpu-ubuntu2204-base"}``; compute specs carry
    ``{"kind": "compute", "machine_type": "n2-standard-8",
    "source_image": ..., "resources": {"CPU": 8}}``.
    """

    def __init__(self, project: str, zone: str, cluster_name: str,
                 node_types: Dict[str, Dict[str, Any]],
                 transport: Optional[Callable] = None,
                 poll_interval_s: float = 5.0,
                 op_timeout_s: float = 900.0):
        self.project = project
        self.zone = zone
        self.cluster = cluster_name
        self.node_types = node_types
        self.transport = transport or LiveTransport()
        self.poll_interval_s = poll_interval_s
        self.op_timeout_s = op_timeout_s
        self._seq = 0

    # -- helpers ----------------------------------------------------------

    def _name(self, kind: str) -> str:
        self._seq += 1
        return f"rtpu-{self.cluster}-{kind}-{self._seq}-{int(time.time())}"

    def _tpu_parent(self) -> str:
        return f"projects/{self.project}/locations/{self.zone}"

    def _wait_op(self, op: dict, base: str) -> dict:
        """Poll a long-running operation to completion."""
        deadline = time.monotonic() + self.op_timeout_s
        while not op.get("done"):
            if time.monotonic() > deadline:
                raise TimeoutError(f"operation {op.get('name')} timed out")
            time.sleep(self.poll_interval_s)
            op = self.transport("GET", f"{base}/{op['name']}")
        if "error" in op:
            raise RuntimeError(f"operation failed: {op['error']}")
        return op

    # -- compute (CPU workers) -------------------------------------------

    def create_nodes(self, node_type: str, count: int) -> List[NodeInfo]:
        spec = self.node_types[node_type]
        assert spec.get("kind", "compute") == "compute", node_type
        out = []
        for _ in range(count):
            name = self._name("compute")
            body = {
                "name": name,
                "machineType": (f"zones/{self.zone}/machineTypes/"
                                f"{spec['machine_type']}"),
                "labels": {"rtpu-cluster": self.cluster,
                           "rtpu-node-type": node_type},
                "disks": [{"boot": True, "initializeParams": {
                    "sourceImage": spec.get(
                        "source_image",
                        "projects/debian-cloud/global/images/family/"
                        "debian-12")}}],
                "networkInterfaces": [{"network": "global/networks/default"}],
            }
            op = self.transport(
                "POST",
                f"{GCE_API}/projects/{self.project}/zones/{self.zone}"
                "/instances", body)
            self._wait_op(
                op, f"{GCE_API}/projects/{self.project}/zones/{self.zone}"
                "/operations")
            out.append(NodeInfo(
                node_id=name, node_type=node_type, slice_id=None,
                resources=dict(spec.get("resources", {"CPU": 1})),
                tags={"rtpu-cluster": self.cluster,
                      "rtpu-node-type": node_type}))
        return out

    # -- TPU slices -------------------------------------------------------

    def create_slice(self, slice_type: str) -> List[NodeInfo]:
        spec = self.node_types[slice_type]
        assert spec.get("kind") == "tpu", slice_type
        acc = spec["accelerator_type"]
        name = self._name("tpu")
        body = {
            "acceleratorType": acc,
            "runtimeVersion": spec.get("runtime_version",
                                       "tpu-ubuntu2204-base"),
            "labels": {"rtpu-cluster": self.cluster,
                       "rtpu-node-type": slice_type},
            "networkConfig": {"enableExternalIps": spec.get(
                "external_ips", False)},
        }
        op = self.transport(
            "POST", f"{TPU_API}/{self._tpu_parent()}/nodes?nodeId={name}",
            body)
        self._wait_op(op, f"{TPU_API}/{self._tpu_parent()}/operations")
        node = self.transport(
            "GET", f"{TPU_API}/{self._tpu_parent()}/nodes/{name}")
        return self._slice_hosts(name, slice_type, acc, node)

    def _slice_hosts(self, name: str, slice_type: str, acc: str,
                     node: dict) -> List[NodeInfo]:
        endpoints = node.get("networkEndpoints") or [{}]
        chips = _chips_per_host(acc)
        out = []
        for i, ep in enumerate(endpoints):
            res = {"TPU": float(chips), name: 1.0}
            if i == 0:
                # slice-head resource: a driver schedules ONE task here,
                # then fans out one per host via the shared slice name
                res[f"TPU-{acc}-head"] = 1.0
            out.append(NodeInfo(
                node_id=f"{name}/host-{i}", node_type=slice_type,
                slice_id=name, resources=res, is_slice_head=(i == 0),
                tags={"rtpu-cluster": self.cluster,
                      "rtpu-node-type": slice_type,
                      "ip": ep.get("ipAddress", "")}))
        return out

    # -- teardown / listing ----------------------------------------------

    def terminate_node(self, node_id: str) -> None:
        if "/host-" in node_id:  # a TPU host cannot die alone
            self.terminate_slice(node_id.split("/", 1)[0])
            return
        op = self.transport(
            "DELETE",
            f"{GCE_API}/projects/{self.project}/zones/{self.zone}"
            f"/instances/{node_id}")
        self._wait_op(
            op, f"{GCE_API}/projects/{self.project}/zones/{self.zone}"
            "/operations")

    def terminate_slice(self, slice_id: str) -> None:
        op = self.transport(
            "DELETE", f"{TPU_API}/{self._tpu_parent()}/nodes/{slice_id}")
        self._wait_op(op, f"{TPU_API}/{self._tpu_parent()}/operations")

    def non_terminated_nodes(self) -> List[NodeInfo]:
        out: List[NodeInfo] = []
        # TPU slices
        resp = self.transport(
            "GET", f"{TPU_API}/{self._tpu_parent()}/nodes")
        for node in resp.get("nodes", []):
            labels = node.get("labels") or {}
            if labels.get("rtpu-cluster") != self.cluster:
                continue
            if node.get("state") in ("DELETING", "TERMINATED", "STOPPED",
                                     "PREEMPTED"):
                continue
            name = node["name"].rsplit("/", 1)[-1]
            ntype = labels.get("rtpu-node-type", "tpu")
            acc = node.get("acceleratorType", "v5litepod-4")
            out.extend(self._slice_hosts(name, ntype, acc, node))
        # compute instances
        resp = self.transport(
            "GET",
            f"{GCE_API}/projects/{self.project}/zones/{self.zone}"
            f"/instances?filter=labels.rtpu-cluster={self.cluster}")
        for inst in resp.get("items", []):
            if inst.get("status") in ("STOPPING", "TERMINATED", "SUSPENDED"):
                continue
            labels = inst.get("labels") or {}
            ntype = labels.get("rtpu-node-type", "cpu-worker")
            spec = self.node_types.get(ntype, {})
            out.append(NodeInfo(
                node_id=inst["name"], node_type=ntype, slice_id=None,
                resources=dict(spec.get("resources", {"CPU": 1})),
                tags=labels))
        return out
