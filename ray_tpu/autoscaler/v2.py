"""Autoscaler v2: explicit per-instance state machine + reconciler.

Role analog: ``python/ray/autoscaler/v2/`` — the instance manager
(``instance_manager/instance_manager.py``) that tracks every cloud
instance through a declared lifecycle instead of v1's stateless
load-diffing, plus a reconciler that converges observed cloud/cluster
state with desired state. States (reference ``instance_storage`` enum
role)::

    QUEUED -> REQUESTED -> ALLOCATED -> RAY_RUNNING
        -> RAY_STOPPING -> TERMINATING -> TERMINATED
    (any) -> ALLOCATION_FAILED

The v1 :class:`~ray_tpu.autoscaler.autoscaler.StandardAutoscaler` remains
the simple default; v2 adds what operators need at fleet scale: idempotent
launches (a crash between request and allocation is reconciled, not
duplicated), visibility into stuck instances, and clean handoff between
"cloud says the VM exists" and "the node registered with the GCS".
"""

from __future__ import annotations

import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeProvider

QUEUED = "QUEUED"
REQUESTED = "REQUESTED"
ALLOCATED = "ALLOCATED"
RAY_RUNNING = "RAY_RUNNING"
RAY_STOPPING = "RAY_STOPPING"
TERMINATING = "TERMINATING"
TERMINATED = "TERMINATED"
ALLOCATION_FAILED = "ALLOCATION_FAILED"

_TRANSITIONS = {
    QUEUED: {REQUESTED, TERMINATED},
    REQUESTED: {ALLOCATED, ALLOCATION_FAILED},
    ALLOCATED: {RAY_RUNNING, TERMINATING},
    RAY_RUNNING: {RAY_STOPPING, TERMINATING},
    RAY_STOPPING: {TERMINATING},
    TERMINATING: {TERMINATED},
    ALLOCATION_FAILED: set(),
    TERMINATED: set(),
}


@dataclass
class Instance:
    instance_id: str                   # manager-assigned, stable
    node_type: str
    status: str = QUEUED
    cloud_id: Optional[str] = None     # provider node id once ALLOCATED
    node_id: Optional[str] = None      # GCS node id once RAY_RUNNING
    launch_request_id: str = ""
    status_history: List[tuple] = field(default_factory=list)

    def transition(self, to: str) -> None:
        if to not in _TRANSITIONS[self.status]:
            raise ValueError(
                f"invalid transition {self.status} -> {to} "
                f"({self.instance_id})")
        self.status_history.append((self.status, time.time()))
        self.status = to


class InstanceManager:
    """Owns the instance table and drives each instance through its
    lifecycle against a :class:`NodeProvider` (reference
    ``instance_manager.py`` role)."""

    def __init__(self, provider: NodeProvider):
        self.provider = provider
        self.instances: Dict[str, Instance] = {}

    # -- desired-state input -------------------------------------------

    def launch(self, node_type: str, count: int = 1) -> List[str]:
        """Queue ``count`` new instances; returns their ids. Idempotency
        handle: callers pass the same launch_request via dedupe_key."""
        req = uuid.uuid4().hex[:8]
        out = []
        for _ in range(count):
            iid = f"inst-{uuid.uuid4().hex[:8]}"
            self.instances[iid] = Instance(iid, node_type,
                                           launch_request_id=req)
            out.append(iid)
        return out

    def terminate(self, instance_id: str) -> None:
        inst = self.instances[instance_id]
        if inst.status in (QUEUED,):
            inst.transition(TERMINATED)
        elif inst.status in (ALLOCATED, RAY_RUNNING, RAY_STOPPING):
            inst.transition(TERMINATING)

    # -- reconciliation loop -------------------------------------------

    def reconcile(self, alive_node_ids: Optional[set] = None) -> None:
        """One convergence pass: push QUEUED to the cloud, adopt cloud
        allocations, bind GCS-alive nodes, and finish terminations.
        ``alive_node_ids``: cloud ids observed alive in the GCS node
        table (RAY_RUNNING evidence)."""
        alive_node_ids = alive_node_ids or set()
        # 1. request queued instances from the provider
        for inst in self.instances.values():
            if inst.status != QUEUED:
                continue
            inst.transition(REQUESTED)
            try:
                infos = self.provider.create_nodes(inst.node_type, 1)
                inst.cloud_id = infos[0].node_id
                inst.transition(ALLOCATED)
            except Exception:
                inst.transition(ALLOCATION_FAILED)
        # 2. cloud view: instances whose VM disappeared are terminated
        live_cloud = {n.node_id for n in self.provider.non_terminated_nodes()}
        for inst in self.instances.values():
            if inst.status in (ALLOCATED, RAY_RUNNING) \
                    and inst.cloud_id not in live_cloud:
                inst.transition(TERMINATING)
            if inst.status == ALLOCATED and inst.cloud_id in alive_node_ids:
                inst.node_id = inst.cloud_id
                inst.transition(RAY_RUNNING)
        # 3. finish terminations
        for inst in self.instances.values():
            if inst.status == TERMINATING:
                if inst.cloud_id in live_cloud:
                    try:
                        self.provider.terminate_node(inst.cloud_id)
                    except Exception:
                        continue  # retry next pass
                inst.transition(TERMINATED)

    # -- views ----------------------------------------------------------

    def by_status(self) -> Dict[str, List[Instance]]:
        out: Dict[str, List[Instance]] = {}
        for inst in self.instances.values():
            out.setdefault(inst.status, []).append(inst)
        return out

    def summary(self) -> Dict[str, Any]:
        return {status: len(v) for status, v in self.by_status().items()}


# ---------------------------------------------------------------------------
# Resource-demand scheduler (reference autoscaler/v2/scheduler.py role)
# ---------------------------------------------------------------------------

_ACTIVE = (QUEUED, REQUESTED, ALLOCATED, RAY_RUNNING)

Bundle = Dict[str, float]


@dataclass
class NodeTypeSpec:
    """Declared node type for v2 (reference ``NodeTypeConfig`` +
    ``node_config`` resources). Unlike v1, resources are DECLARED here —
    the scheduler never peeks at the provider."""

    name: str
    resources: Dict[str, float]
    min_workers: int = 0
    max_workers: int = 10


@dataclass
class SchedulingDecision:
    """Output of one scheduling pass — pure data, applied by
    :class:`AutoscalerV2` (reference ``SchedulingReply`` role)."""

    launches: Dict[str, int] = field(default_factory=dict)
    terminations: List[str] = field(default_factory=list)   # instance ids
    infeasible: List[Bundle] = field(default_factory=list)
    packing: Dict[str, int] = field(default_factory=dict)   # iid -> bundles

    def summary(self) -> Dict[str, Any]:
        return {"launches": dict(self.launches),
                "terminations": list(self.terminations),
                "infeasible": len(self.infeasible)}


class ResourceDemandScheduler:
    """Bin-pack pending demand over the instance table (reference
    ``autoscaler/v2/scheduler.py`` ResourceDemandScheduler).

    A pure function of (demand, instances, idle set): no provider calls,
    no clock — the same inputs always produce the same decision, which is
    what makes v2 scheduling testable and auditable (the reference logs
    every decision for exactly this reason).

    Passes, in order (reference ``_sched_*`` pipeline):

    1. **min_workers floors** — launch up to each type's minimum counting
       every non-terminal instance (QUEUED/REQUESTED included: launches
       are idempotent against the instance table, never the cloud).
    2. **first-fit-decreasing bin-pack** of demand bundles onto free
       capacity of active instances, then onto virtual instances of
       already-planned launches, then onto new launches (respecting
       max_workers). Unpackable bundles are reported ``infeasible``.
    3. **idle release** — idle RAY_RUNNING instances that received no
       bundle in pass 2 and aren't needed for min_workers are terminated.
    """

    def __init__(self, node_types: List[NodeTypeSpec]):
        self.node_types = list(node_types)
        self._by_name = {t.name: t for t in node_types}

    def schedule(self, demand: List[Bundle],
                 instances: Dict[str, Instance],
                 idle_instance_ids: Optional[set] = None,
                 available: Optional[Dict[str, Bundle]] = None,
                 ) -> SchedulingDecision:
        """``available``: per-instance AVAILABLE resources (instance_id ->
        free bundle), typically from the GCS node table. Instances listed
        here bin-pack against their free capacity; unlisted ones (and all
        pre-RAY_RUNNING states, which have no load yet) fall back to the
        type's full declared resources. Without this input a saturated
        cluster looks infinitely packable and never scales up (ADVICE r5)."""
        idle = set(idle_instance_ids or ())
        available = available or {}
        dec = SchedulingDecision()

        active = [i for i in instances.values() if i.status in _ACTIVE
                  and i.node_type in self._by_name]
        counts: Dict[str, int] = {}
        for inst in active:
            counts[inst.node_type] = counts.get(inst.node_type, 0) + 1

        # pass 1: min_workers floors
        for t in self.node_types:
            short = t.min_workers - (counts.get(t.name, 0)
                                     + dec.launches.get(t.name, 0))
            if short > 0:
                dec.launches[t.name] = dec.launches.get(t.name, 0) + short

        # pass 2: FFD bin-pack. Track per-slot free capacity; slots are
        # (instance_id | planned-launch marker, resources) — seeded from
        # each instance's AVAILABLE capacity when known, never the full
        # declared resources of a node that is already running load.
        slots: List[tuple] = [
            (i.instance_id,
             dict(available.get(i.instance_id)
                  if i.instance_id in available
                  else self._by_name[i.node_type].resources))
            for i in active]
        for name, k in dec.launches.items():
            slots.extend(("<new>", dict(self._by_name[name].resources))
                         for _ in range(k))
        for bundle in sorted(demand, key=lambda b: -sum(b.values())):
            if self._fit(bundle, slots, dec):
                continue
            t = self._pick_type(bundle, counts, dec.launches)
            if t is None:
                dec.infeasible.append(dict(bundle))
                continue
            dec.launches[t.name] = dec.launches.get(t.name, 0) + 1
            slots.append(("<new>", dict(t.resources)))
            self._fit(bundle, slots, dec)

        # pass 3: idle release (never below min_workers, never a packed
        # instance)
        for t in self.node_types:
            running = [i for i in active if i.node_type == t.name
                       and i.status == RAY_RUNNING]
            releasable = [i for i in running
                          if i.instance_id in idle
                          and i.instance_id not in dec.packing]
            keep = max(t.min_workers, len(running) - len(releasable))
            n_release = len(running) - keep
            dec.terminations.extend(
                i.instance_id for i in releasable[:max(0, n_release)])
        return dec

    @staticmethod
    def _fit(bundle: Bundle, slots: List[tuple],
             dec: SchedulingDecision) -> bool:
        for iid, free in slots:
            if all(free.get(k, 0.0) >= v for k, v in bundle.items()):
                for k, v in bundle.items():
                    free[k] = free.get(k, 0.0) - v
                if iid != "<new>":
                    dec.packing[iid] = dec.packing.get(iid, 0) + 1
                return True
        return False

    def _pick_type(self, bundle: Bundle, counts: Dict[str, int],
                   launches: Dict[str, int]) -> Optional[NodeTypeSpec]:
        for t in self.node_types:
            if counts.get(t.name, 0) + launches.get(t.name, 0) \
                    >= t.max_workers:
                continue
            if all(t.resources.get(k, 0.0) >= v for k, v in bundle.items()):
                return t
        return None


class AutoscalerV2:
    """The v2 loop: demand -> scheduler -> instance manager (reference
    ``autoscaler/v2/autoscaler.py`` role). One ``update()`` is one
    converge step; all state lives in the instance table, so a crashed
    autoscaler resumes by re-reading it."""

    def __init__(self, provider: NodeProvider,
                 node_types: List[NodeTypeSpec],
                 load_source: Optional[Any] = None,
                 idle_timeout_s: float = 60.0,
                 clock: Any = time.monotonic):
        self.im = InstanceManager(provider)
        self.scheduler = ResourceDemandScheduler(node_types)
        self.load_source = load_source
        self.idle_timeout_s = idle_timeout_s
        self._clock = clock  # injectable for deterministic tests
        self._last_busy: Dict[str, float] = {}

    def update(self, demand: Optional[List[Bundle]] = None,
               alive_node_ids: Optional[set] = None,
               busy_instance_ids: Optional[set] = None,
               available_resources: Optional[Dict[str, Bundle]] = None,
               ) -> SchedulingDecision:
        """One pass. ``busy_instance_ids``: instances with resources in
        use (idle-timeout input); ``alive_node_ids``: cloud ids seen in
        the GCS node table; ``available_resources``: per-instance free
        capacity from the node table, so pending demand packs against
        what is actually free instead of each node's declared total."""
        demand = list(demand or [])
        if self.load_source is not None:
            demand += list(self.load_source() or [])

        now = self._clock()
        busy = set(busy_instance_ids or ())
        idle = set()
        for iid, inst in self.im.instances.items():
            if inst.status != RAY_RUNNING:
                if inst.status in (TERMINATED, ALLOCATION_FAILED):
                    self._last_busy.pop(iid, None)
                continue
            if iid in busy or iid not in self._last_busy:
                self._last_busy[iid] = now
            if now - self._last_busy[iid] >= self.idle_timeout_s:
                idle.add(iid)

        dec = self.scheduler.schedule(demand, self.im.instances, idle,
                                      available=available_resources)
        for name, k in dec.launches.items():
            self.im.launch(name, k)
        for iid in dec.terminations:
            self.im.terminate(iid)
        self.im.reconcile(alive_node_ids)
        return dec
