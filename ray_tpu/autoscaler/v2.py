"""Autoscaler v2: explicit per-instance state machine + reconciler.

Role analog: ``python/ray/autoscaler/v2/`` — the instance manager
(``instance_manager/instance_manager.py``) that tracks every cloud
instance through a declared lifecycle instead of v1's stateless
load-diffing, plus a reconciler that converges observed cloud/cluster
state with desired state. States (reference ``instance_storage`` enum
role)::

    QUEUED -> REQUESTED -> ALLOCATED -> RAY_RUNNING
        -> RAY_STOPPING -> TERMINATING -> TERMINATED
    (any) -> ALLOCATION_FAILED

The v1 :class:`~ray_tpu.autoscaler.autoscaler.StandardAutoscaler` remains
the simple default; v2 adds what operators need at fleet scale: idempotent
launches (a crash between request and allocation is reconciled, not
duplicated), visibility into stuck instances, and clean handoff between
"cloud says the VM exists" and "the node registered with the GCS".
"""

from __future__ import annotations

import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeProvider

QUEUED = "QUEUED"
REQUESTED = "REQUESTED"
ALLOCATED = "ALLOCATED"
RAY_RUNNING = "RAY_RUNNING"
RAY_STOPPING = "RAY_STOPPING"
TERMINATING = "TERMINATING"
TERMINATED = "TERMINATED"
ALLOCATION_FAILED = "ALLOCATION_FAILED"

_TRANSITIONS = {
    QUEUED: {REQUESTED, TERMINATED},
    REQUESTED: {ALLOCATED, ALLOCATION_FAILED},
    ALLOCATED: {RAY_RUNNING, TERMINATING},
    RAY_RUNNING: {RAY_STOPPING, TERMINATING},
    RAY_STOPPING: {TERMINATING},
    TERMINATING: {TERMINATED},
    ALLOCATION_FAILED: set(),
    TERMINATED: set(),
}


@dataclass
class Instance:
    instance_id: str                   # manager-assigned, stable
    node_type: str
    status: str = QUEUED
    cloud_id: Optional[str] = None     # provider node id once ALLOCATED
    node_id: Optional[str] = None      # GCS node id once RAY_RUNNING
    launch_request_id: str = ""
    status_history: List[tuple] = field(default_factory=list)

    def transition(self, to: str) -> None:
        if to not in _TRANSITIONS[self.status]:
            raise ValueError(
                f"invalid transition {self.status} -> {to} "
                f"({self.instance_id})")
        self.status_history.append((self.status, time.time()))
        self.status = to


class InstanceManager:
    """Owns the instance table and drives each instance through its
    lifecycle against a :class:`NodeProvider` (reference
    ``instance_manager.py`` role)."""

    def __init__(self, provider: NodeProvider):
        self.provider = provider
        self.instances: Dict[str, Instance] = {}

    # -- desired-state input -------------------------------------------

    def launch(self, node_type: str, count: int = 1) -> List[str]:
        """Queue ``count`` new instances; returns their ids. Idempotency
        handle: callers pass the same launch_request via dedupe_key."""
        req = uuid.uuid4().hex[:8]
        out = []
        for _ in range(count):
            iid = f"inst-{uuid.uuid4().hex[:8]}"
            self.instances[iid] = Instance(iid, node_type,
                                           launch_request_id=req)
            out.append(iid)
        return out

    def terminate(self, instance_id: str) -> None:
        inst = self.instances[instance_id]
        if inst.status in (QUEUED,):
            inst.transition(TERMINATED)
        elif inst.status in (ALLOCATED, RAY_RUNNING, RAY_STOPPING):
            inst.transition(TERMINATING)

    # -- reconciliation loop -------------------------------------------

    def reconcile(self, alive_node_ids: Optional[set] = None) -> None:
        """One convergence pass: push QUEUED to the cloud, adopt cloud
        allocations, bind GCS-alive nodes, and finish terminations.
        ``alive_node_ids``: cloud ids observed alive in the GCS node
        table (RAY_RUNNING evidence)."""
        alive_node_ids = alive_node_ids or set()
        # 1. request queued instances from the provider
        for inst in self.instances.values():
            if inst.status != QUEUED:
                continue
            inst.transition(REQUESTED)
            try:
                infos = self.provider.create_nodes(inst.node_type, 1)
                inst.cloud_id = infos[0].node_id
                inst.transition(ALLOCATED)
            except Exception:
                inst.transition(ALLOCATION_FAILED)
        # 2. cloud view: instances whose VM disappeared are terminated
        live_cloud = {n.node_id for n in self.provider.non_terminated_nodes()}
        for inst in self.instances.values():
            if inst.status in (ALLOCATED, RAY_RUNNING) \
                    and inst.cloud_id not in live_cloud:
                inst.transition(TERMINATING)
            if inst.status == ALLOCATED and inst.cloud_id in alive_node_ids:
                inst.node_id = inst.cloud_id
                inst.transition(RAY_RUNNING)
        # 3. finish terminations
        for inst in self.instances.values():
            if inst.status == TERMINATING:
                if inst.cloud_id in live_cloud:
                    try:
                        self.provider.terminate_node(inst.cloud_id)
                    except Exception:
                        continue  # retry next pass
                inst.transition(TERMINATED)

    # -- views ----------------------------------------------------------

    def by_status(self) -> Dict[str, List[Instance]]:
        out: Dict[str, List[Instance]] = {}
        for inst in self.instances.values():
            out.setdefault(inst.status, []).append(inst)
        return out

    def summary(self) -> Dict[str, Any]:
        return {status: len(v) for status, v in self.by_status().items()}
