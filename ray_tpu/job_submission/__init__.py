"""Job submission: run driver scripts as supervised subprocesses.

Role analog: ``dashboard/modules/job`` (``JobManager :56`` spawns a
``JobSupervisor :49`` actor which runs the entrypoint as a subprocess) and
the ``JobSubmissionClient`` SDK. Job state lives in the GCS KV so any
client on the cluster can query it.
"""

from ray_tpu.job_submission.job_manager import (
    JobInfo,
    JobStatus,
    JobSubmissionClient,
)

__all__ = ["JobSubmissionClient", "JobStatus", "JobInfo"]
