"""JobSupervisor actor + JobSubmissionClient.

Role analog: ``dashboard/modules/job/job_manager.py:56`` /
``job_head.py:142``. A submitted job = a JobSupervisor actor running the
entrypoint shell command as a subprocess, streaming logs to a file and
recording status transitions in the GCS KV (PENDING → RUNNING →
SUCCEEDED/FAILED/STOPPED).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import tempfile
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

_KV_NS = "job"


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"

    TERMINAL = (SUCCEEDED, FAILED, STOPPED)


@dataclass
class JobInfo:
    job_id: str
    entrypoint: str
    status: str = JobStatus.PENDING
    message: str = ""
    start_time: Optional[float] = None
    end_time: Optional[float] = None
    metadata: Dict[str, Any] = field(default_factory=dict)
    log_path: str = ""
    return_code: Optional[int] = None
    pgid: Optional[int] = None     # entrypoint's process group (for stop)

    def to_json(self) -> bytes:
        return json.dumps(vars(self)).encode()

    @classmethod
    def from_json(cls, blob: bytes) -> "JobInfo":
        return cls(**json.loads(blob))


def _kv_put(job_id: str, info: JobInfo) -> None:
    import ray_tpu.core.runtime as rt

    rt._get_runtime().kv_op("put", job_id, info.to_json(), _KV_NS, True)


def _kv_get(job_id: str) -> Optional[JobInfo]:
    import ray_tpu.core.runtime as rt

    blob = rt._get_runtime().kv_op("get", job_id, _KV_NS)
    return JobInfo.from_json(blob) if blob else None


def _kv_keys() -> List[str]:
    import ray_tpu.core.runtime as rt

    return rt._get_runtime().kv_op("keys", "", _KV_NS)


class JobSupervisor:
    """Actor that owns one job subprocess."""

    def __init__(self, job_id: str, entrypoint: str,
                 runtime_env: Optional[Dict[str, Any]] = None,
                 metadata: Optional[Dict[str, Any]] = None):
        self.job_id = job_id
        self.entrypoint = entrypoint
        self.runtime_env = runtime_env or {}
        log_dir = os.path.join(tempfile.gettempdir(), "rtpu-jobs")
        os.makedirs(log_dir, exist_ok=True)
        self.info = JobInfo(
            job_id=job_id, entrypoint=entrypoint,
            metadata=metadata or {},
            log_path=os.path.join(log_dir, f"{job_id}.log"),
        )
        self.proc: Optional[subprocess.Popen] = None
        _kv_put(job_id, self.info)

    def run(self) -> str:
        """Start the subprocess and wait for completion (the actor is
        occupied for the job's duration, like the reference supervisor)."""
        # A stop may have landed before we started: honor it and never
        # spawn the entrypoint.
        kv = _kv_get(self.job_id)
        if kv is not None and kv.status == JobStatus.STOPPED:
            self.info.status = JobStatus.STOPPED
            self.info.end_time = time.time()
            _kv_put(self.job_id, self.info)
            return self.info.status
        env = dict(os.environ)
        env.update({k: str(v) for k, v in
                    self.runtime_env.get("env_vars", {}).items()})
        cwd = self.runtime_env.get("working_dir") or None
        self.info.status = JobStatus.RUNNING
        self.info.start_time = time.time()
        _kv_put(self.job_id, self.info)
        with open(self.info.log_path, "wb") as logf:
            self.proc = subprocess.Popen(
                self.entrypoint, shell=True, stdout=logf,
                stderr=subprocess.STDOUT, env=env, cwd=cwd,
                start_new_session=True,
            )
            # publish the process group so stop_job can kill the
            # entrypoint even while this actor is occupied by wait()
            self.info.pgid = os.getpgid(self.proc.pid)
            _kv_put(self.job_id, self.info)
            # close the stop-vs-spawn race: a stop that raced between our
            # RUNNING write and the pgid publish couldn't killpg — do it
            # for them now that the pgid exists
            kv = _kv_get(self.job_id)
            if kv is not None and kv.status == JobStatus.STOPPED:
                try:
                    os.killpg(self.info.pgid, signal.SIGTERM)
                except (ProcessLookupError, PermissionError):
                    pass
            rc = self.proc.wait()
        self.info.return_code = rc
        self.info.end_time = time.time()
        # stop_job writes STOPPED straight to the KV while this actor is
        # occupied here — re-read it so a stop isn't overwritten by the
        # SIGTERM'd child's exit status.
        kv_info = _kv_get(self.job_id)
        if kv_info is not None and kv_info.status == JobStatus.STOPPED:
            self.info.status = JobStatus.STOPPED
        elif rc == 0:
            self.info.status = JobStatus.SUCCEEDED
        else:
            self.info.status = JobStatus.FAILED
            self.info.message = f"entrypoint exited with code {rc}"
        _kv_put(self.job_id, self.info)
        return self.info.status

    def stop(self) -> None:
        self.info.status = JobStatus.STOPPED
        _kv_put(self.job_id, self.info)
        if self.proc is not None and self.proc.poll() is None:
            os.killpg(os.getpgid(self.proc.pid), signal.SIGTERM)


class JobSubmissionClient:
    """Driver-side SDK (reference ``ray.job_submission.JobSubmissionClient``)."""

    def __init__(self, address: Optional[str] = None):
        import ray_tpu

        if not ray_tpu.is_initialized():
            ray_tpu.init(ignore_reinit_error=True)
        self._supervisors: Dict[str, Any] = {}
        self._run_refs: Dict[str, Any] = {}

    def submit_job(self, *, entrypoint: str,
                   runtime_env: Optional[Dict[str, Any]] = None,
                   metadata: Optional[Dict[str, Any]] = None,
                   submission_id: Optional[str] = None) -> str:
        import ray_tpu

        job_id = submission_id or f"rtpu-job-{uuid.uuid4().hex[:10]}"
        # Record PENDING before the supervisor exists so status queries
        # never race actor startup.
        _kv_put(job_id, JobInfo(job_id=job_id, entrypoint=entrypoint,
                                metadata=metadata or {}))
        sup_cls = ray_tpu.remote(JobSupervisor)
        sup = sup_cls.options(name=f"_job_supervisor_{job_id}",
                              num_cpus=0).remote(
            job_id, entrypoint, runtime_env, metadata)
        self._supervisors[job_id] = sup
        self._run_refs[job_id] = sup.run.remote()
        return job_id

    def get_job_status(self, job_id: str) -> str:
        info = _kv_get(job_id)
        if info is None:
            raise ValueError(f"unknown job {job_id!r}")
        return info.status

    def get_job_info(self, job_id: str) -> JobInfo:
        info = _kv_get(job_id)
        if info is None:
            raise ValueError(f"unknown job {job_id!r}")
        return info

    def get_job_logs(self, job_id: str) -> str:
        info = self.get_job_info(job_id)
        if info.log_path and os.path.exists(info.log_path):
            with open(info.log_path, errors="replace") as f:
                return f.read()
        return ""

    def list_jobs(self) -> List[JobInfo]:
        out = []
        for key in _kv_keys():
            info = _kv_get(key)
            if info:
                out.append(info)
        return out

    def stop_job(self, job_id: str) -> bool:
        import ray_tpu

        sup = self._supervisors.get(job_id)
        if sup is None:
            try:
                sup = ray_tpu.get_actor(f"_job_supervisor_{job_id}")
            except ValueError:
                return False
        # stop() must preempt the running run() call: the supervisor actor
        # is occupied by wait(), so flag the KV and kill the entrypoint's
        # process group directly (it was started in its own session, so
        # killing the supervisor alone would orphan it).
        info = _kv_get(job_id)
        if info is None:
            return False  # unknown job — nothing to stop
        if info.status not in JobStatus.TERMINAL:
            info.status = JobStatus.STOPPED
            _kv_put(job_id, info)
        # The supervisor cooperates with the STOPPED flag (it refuses to
        # spawn, or killpgs its own child right after publishing the
        # pgid), so every interleaving is covered as long as we do NOT
        # kill the supervisor before the pgid question is settled.
        pgid = info.pgid
        if pgid is None:
            deadline = time.monotonic() + 5.0
            while pgid is None and time.monotonic() < deadline:
                time.sleep(0.05)
                latest = _kv_get(job_id)
                pgid = latest.pgid if latest else None
                if latest and latest.status in JobStatus.TERMINAL and \
                        latest.end_time is not None:
                    break  # supervisor finished the job's lifecycle
        if pgid:
            try:
                os.killpg(pgid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
            try:
                ray_tpu.kill(sup)
            except Exception:
                pass
        # without a pgid the supervisor stays alive to enforce the STOPPED
        # flag itself (killing it here could orphan a mid-spawn entrypoint)
        return True

    def wait_until_finished(self, job_id: str,
                            timeout: float = 300.0) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            status = self.get_job_status(job_id)
            if status in JobStatus.TERMINAL:
                return status
            time.sleep(0.2)
        raise TimeoutError(f"job {job_id} not finished in {timeout}s")
