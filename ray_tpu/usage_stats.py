"""Usage stats: opt-out local usage reporting.

Role analog: ``python/ray/_private/usage/usage_lib.py`` — Ray collects
cluster metadata (version, node count, libraries imported) and reports it
unless the user opts out. This build runs in zero-egress environments, so
the collector writes the SAME report shape to a local file
(``<session_dir>/usage_stats.json``); an operator-side shipper (or
nothing) decides what leaves the machine — strictly more conservative
than the reference's HTTP POST.

Opt-out: ``RTPU_USAGE_STATS_ENABLED=0`` (reference
``RAY_USAGE_STATS_ENABLED`` role). Nothing is collected when disabled.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Any, Dict

_LIBRARIES = ("data", "train", "tune", "serve", "rllib")


def usage_stats_enabled() -> bool:
    return os.environ.get("RTPU_USAGE_STATS_ENABLED", "1") not in (
        "0", "false", "no")


def collect_usage(rt) -> Dict[str, Any]:
    """Build the usage record from a live runtime (cheap: no RPCs beyond
    the cached node view)."""
    from ray_tpu._version import __version__

    libs = [lib for lib in _LIBRARIES
            if f"ray_tpu.{lib}" in sys.modules]
    try:
        n_nodes = 1
        if rt.cluster is not None:
            n_nodes = len([n for n in rt.cluster._nodes() if n["alive"]])
    except Exception:
        n_nodes = 1
    return {
        "schema_version": 1,
        "ray_tpu_version": __version__,
        "python_version": sys.version.split()[0],
        "collected_at": time.time(),
        "session_id": rt.session,
        "num_nodes": n_nodes,
        "total_resources": dict(rt.total),
        "libraries_used": libs,
        "worker_zygote": True,
    }


def write_usage_report(rt) -> str:
    """Write the report under the session dir; returns the path ('' when
    disabled or on failure — usage reporting must never break anything)."""
    if not usage_stats_enabled():
        return ""
    try:
        path = os.path.join(rt.session_dir, "usage_stats.json")
        with open(path, "w") as f:
            json.dump(collect_usage(rt), f, indent=1)
        return path
    except Exception:
        return ""
