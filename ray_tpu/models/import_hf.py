"""HuggingFace checkpoint import: real weights into the ray_tpu model zoo.

Role analog: the reference ecosystem's checkpoint interop (RLlib/Train
users load pretrained torch checkpoints; a TPU framework must ingest the
same artifacts). Maps a ``transformers`` Llama-family state dict
(LlamaForCausalLM / MistralForCausalLM / Qwen2ForCausalLM — the
architectures our ``TransformerConfig`` reproduces exactly: RMSNorm,
RoPE, GQA, SwiGLU, optional Qwen2 q/k/v biases) onto the scanned-layer
param pytree of ``models/transformer.py``.

Conventions handled:

- torch ``nn.Linear`` stores ``W [out, in]`` computing ``x @ W.T`` — our
  einsum weights are ``[in, out]``-shaped, so every projection is
  transposed (then reshaped to split heads);
- per-layer tensors are STACKED on a leading layer axis (our layers run
  under ``lax.scan``);
- rotate-half RoPE matches HF's (first/second half split, same theta);
- tied embeddings reuse ``embed``; untied checkpoints fill ``lm_head``.

Verified by an exact logits-parity test against ``transformers`` on a
randomly initialized tiny Llama (tests/test_models.py).
"""

from __future__ import annotations

from typing import Any, Dict, Mapping

import numpy as np

from ray_tpu.models.config import TransformerConfig

Params = Dict[str, Any]


def config_from_hf(hf_config: Any) -> TransformerConfig:
    """TransformerConfig from a ``transformers`` LlamaConfig/MistralConfig
    (duck-typed: any object with the HF attribute names)."""
    scaling = getattr(hf_config, "rope_scaling", None)
    if scaling:
        raise ValueError(
            f"rope_scaling={scaling!r} is not supported: ray_tpu's "
            "rotary tables are unscaled, so importing (e.g.) a "
            "Llama-3.1+ checkpoint would produce silently wrong "
            "frequencies")
    if getattr(hf_config, "attention_bias", False):
        # HF Llama's attention_bias biases o_proj too, which the forward
        # does not model — refuse rather than import silently wrong
        raise ValueError(
            "attention_bias=True (q/k/v AND o_proj biases) is not "
            "supported; only Qwen2-style q/k/v-only biases are")
    qwen2 = getattr(hf_config, "model_type", "") == "qwen2"
    window = getattr(hf_config, "sliding_window", None) or 0
    attn_windows = None
    if qwen2:
        if window and getattr(hf_config, "use_sliding_window", False):
            # Per-layer windows. transformers reads layer_types per
            # layer when present; older configs use the prefix rule
            # (full attention below max_window_layers, SWA above).
            layer_types = getattr(hf_config, "layer_types", None)
            if layer_types:
                known = {"sliding_attention", "full_attention"}
                bad = set(layer_types) - known
                if bad or len(layer_types) != hf_config.num_hidden_layers:
                    # refuse-loudly policy: an unknown attention kind
                    # (chunked/linear/...) or a mis-sized list must not
                    # import as silently-wrong full attention
                    raise ValueError(
                        f"unsupported layer_types (unknown kinds {sorted(bad)}"
                        f", len {len(layer_types)} vs "
                        f"{hf_config.num_hidden_layers} layers)")
                per_layer = tuple(
                    int(window) if t == "sliding_attention" else 0
                    for t in layer_types)
            else:
                full = int(getattr(hf_config, "max_window_layers", 0))
                per_layer = tuple(
                    0 if i < full else int(window)
                    for i in range(hf_config.num_hidden_layers))
            # minimal repeating period keeps the grouped layer scan
            # small (a prefix rule has no short period and pays a
            # one-group trace; the common alternating/uniform cases
            # reduce to 1-2 entries)
            attn_windows = _min_period(per_layer)
            if set(attn_windows) == {0}:
                attn_windows = None
        window = 0  # HF ignores sliding_window unless use_sliding_window
    return TransformerConfig(
        vocab_size=hf_config.vocab_size,
        d_model=hf_config.hidden_size,
        n_layers=hf_config.num_hidden_layers,
        n_heads=hf_config.num_attention_heads,
        n_kv_heads=getattr(hf_config, "num_key_value_heads",
                           hf_config.num_attention_heads),
        head_dim=getattr(hf_config, "head_dim", None),
        d_ff=hf_config.intermediate_size,
        max_seq_len=hf_config.max_position_embeddings,
        rope_theta=float(getattr(hf_config, "rope_theta", 10000.0)),
        sliding_window=int(window),
        attn_windows=attn_windows,
        tie_embeddings=bool(getattr(hf_config, "tie_word_embeddings",
                                    False)),
        norm_eps=float(getattr(hf_config, "rms_norm_eps", 1e-6)),
        attn_qkv_bias=qwen2,  # Qwen2 biases q/k/v only (o stays clean)
        mlp="swiglu", norm="rms", positions="rope",
        dtype="float32", param_dtype="float32",
    )


def _min_period(pat: tuple) -> tuple:
    """Smallest repeating prefix generating ``pat`` (itself if aperiodic)."""
    n = len(pat)
    for p in range(1, n):
        if n % p == 0 and pat[:p] * (n // p) == pat:
            return pat[:p]
    return pat


def _np(w, dtype) -> np.ndarray:
    """torch tensor (or array) -> numpy in the TARGET param dtype (no
    transient f32 blow-up: an 8B bf16 checkpoint stays bf16-sized)."""
    if hasattr(w, "detach"):
        import torch

        w = w.detach().cpu()
        if w.dtype == torch.bfloat16:  # numpy has no native bf16 bridge
            w = w.float()
        w = w.numpy()
    import jax.numpy as jnp

    return np.asarray(w).astype(jnp.dtype(dtype))


def import_hf_llama(state_dict: Mapping[str, Any],
                    config: TransformerConfig) -> Params:
    """Build the ray_tpu param pytree from a Llama-family HF state dict.

    ``state_dict``: ``model.state_dict()`` of a ``LlamaForCausalLM`` /
    ``MistralForCausalLM`` (torch tensors or numpy arrays).
    """
    c = config
    if c.mlp != "swiglu" or c.norm != "rms" or c.positions != "rope":
        raise ValueError(
            "import_hf_llama maps Llama-family architectures only "
            f"(swiglu/rms/rope); config has {c.mlp}/{c.norm}/{c.positions}")
    sd = dict(state_dict)
    pre = "model." if "model.embed_tokens.weight" in sd else ""
    d, hd, h, kv, L = c.d_model, c.hdim, c.n_heads, c.kv_heads, c.n_layers
    pdt = c.param_dtype
    consumed = set()

    def take(key):
        consumed.add(key)
        return sd[key]

    def raw(i: int, name: str):
        return _np(take(f"{pre}layers.{i}.{name}.weight"), pdt)

    def lin(i: int, name: str):
        return raw(i, name).T  # Linear [out, in] -> einsum [in, out]

    stack = lambda mats: np.stack(mats, axis=0)
    layers: Params = {
        "attn_norm": stack([raw(i, "input_layernorm")
                            for i in range(L)]),
        "wq": stack([lin(i, "self_attn.q_proj").reshape(d, h, hd)
                     for i in range(L)]),
        "wk": stack([lin(i, "self_attn.k_proj").reshape(d, kv, hd)
                     for i in range(L)]),
        "wv": stack([lin(i, "self_attn.v_proj").reshape(d, kv, hd)
                     for i in range(L)]),
        "wo": stack([lin(i, "self_attn.o_proj").reshape(h, hd, d)
                     for i in range(L)]),
        "mlp_norm": stack([raw(i, "post_attention_layernorm")
                           for i in range(L)]),
        "w_gate": stack([lin(i, "mlp.gate_proj") for i in range(L)]),
        "w_up": stack([lin(i, "mlp.up_proj") for i in range(L)]),
        "w_down": stack([lin(i, "mlp.down_proj") for i in range(L)]),
    }
    if c.attn_qkv_bias:  # Qwen2-style q/k/v biases, head-split
        def bias(i, name, heads):
            return _np(take(f"{pre}layers.{i}.self_attn.{name}.bias"),
                       pdt).reshape(heads, hd)

        layers["bq"] = stack([bias(i, "q_proj", h) for i in range(L)])
        layers["bk"] = stack([bias(i, "k_proj", kv) for i in range(L)])
        layers["bv"] = stack([bias(i, "v_proj", kv) for i in range(L)])
    params: Params = {
        "embed": _np(take(f"{pre}embed_tokens.weight"), pdt),
        "layers": layers,
        "final_norm": _np(take(f"{pre}norm.weight"), pdt),
    }
    if not c.tie_embeddings:
        if "lm_head.weight" in sd:
            params["lm_head"] = _np(take("lm_head.weight"), pdt).T
        else:  # tied checkpoint imported into an untied config
            params["lm_head"] = params["embed"].T.copy()
    else:
        consumed.add("lm_head.weight")  # alias of embed when present

    # Strict-consumption check (torch load_state_dict strict=True role):
    # an architecture this mapping does NOT model (Qwen3 q/k norms,
    # MoE routers, ...) must fail loudly, never silently drop
    # tensors. Non-parameter buffers (rotary inv_freq caches) are
    # the only tolerated leftovers.
    leftovers = [k for k in sd
                 if k not in consumed and not k.endswith("inv_freq")]
    if leftovers:
        raise ValueError(
            "state dict has tensors this Llama-family mapping does not "
            f"consume (unsupported architecture?): {sorted(leftovers)[:8]}"
            f"{' ...' if len(leftovers) > 8 else ''}")

    import jax.numpy as jnp

    jdt = jnp.dtype(pdt)
    return {k: (jnp.asarray(v, jdt) if not isinstance(v, dict)
                else {kk: jnp.asarray(vv, jdt) for kk, vv in v.items()})
            for k, v in params.items()}


def load_hf_llama(model_name_or_path: str):
    """Convenience: load with ``transformers`` and import. Returns
    (config, params). Requires the checkpoint locally (zero-egress
    environments must pre-download)."""
    from transformers import AutoConfig, AutoModelForCausalLM

    hf_cfg = AutoConfig.from_pretrained(model_name_or_path)
    config = config_from_hf(hf_cfg)
    model = AutoModelForCausalLM.from_pretrained(model_name_or_path)
    params = import_hf_llama(model.state_dict(), config)
    return config, params
