"""Model configs + presets for the built-in transformer family.

The reference ships no model zoo of its own (RLlib's catalogs build
encoders per-framework, ``rllib/core/models/``; Train wraps user torch
models). Here the model family is first-class because the flagship
benchmark is LLM training (BASELINE.json north star: Llama-3-8B ≥45% MFU),
so the framework owns a TPU-tuned transformer the way the reference's
release benchmarks own ``torch_benchmark.py`` workloads
(``release/air_tests/air_benchmarks/workloads/``).

Everything is static at trace time: a config is hashable and is passed as a
static argument to jitted functions.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Optional, Tuple


@dataclass(frozen=True)
class TransformerConfig:
    """Hashable, trace-static description of a decoder-only transformer."""

    vocab_size: int = 32000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: Optional[int] = None   # None => MHA (= n_heads); < n_heads => GQA
    head_dim: Optional[int] = None     # None => d_model // n_heads
    d_ff: Optional[int] = None         # None => 4*d_model (gelu) / ~8/3*d_model (swiglu)
    max_seq_len: int = 2048

    # architecture family knobs
    mlp: str = "swiglu"                # "swiglu" (llama) | "gelu" (gpt2)
    norm: str = "rms"                  # "rms" (llama) | "layer" (gpt2)
    positions: str = "rope"            # "rope" (llama) | "learned" (gpt2)
    rope_theta: float = 500000.0
    tie_embeddings: bool = False
    # norm epsilon; None = family default (rms 1e-6, layer 1e-5). Real
    # checkpoints vary (llama-2/3 and mistral use 1e-5) — HF import sets
    # this from rms_norm_eps so parity is exact.
    norm_eps: Optional[float] = None
    # q/k/v projection biases (Qwen2; o_proj stays bias-free)
    attn_qkv_bias: bool = False

    # mixture of experts (0 => dense)
    num_experts: int = 0
    expert_top_k: int = 2
    expert_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01

    # sliding-window (local) attention: each token attends to its last N
    # keys only (0 = full causal). Mistral-style; applies to every layer.
    sliding_window: int = 0
    # per-layer window PATTERN (Gemma-2 alternation): a repeating tuple of
    # windows, one per layer, 0 = global. E.g. (4096, 0) = sliding on even
    # layers, global on odd. Overrides ``sliding_window`` when set;
    # n_layers must divide by the pattern length. The training stack scans
    # layer GROUPS of the pattern length so each sub-layer's window stays
    # static (the banded kernels need static block liveness).
    attn_windows: Optional[Tuple[int, ...]] = None
    # attention-logit tanh soft-capping (Gemma-2: 50.0; 0 = off), applied
    # inside every attention impl before masking — incl. the Pallas
    # kernels' fwd and bwd, so training matches real checkpoints exactly
    attn_softcap: float = 0.0

    # pipeline parallelism: microbatch count for the GPipe schedule when
    # the ambient mesh has pp > 1 (0 => 2 * pp, the usual bubble/memory
    # compromise); batch size must divide by it
    pp_microbatches: int = 0

    # numerics / memory
    dtype: str = "bfloat16"            # activation/param compute dtype
    param_dtype: str = "float32"       # master param dtype
    remat: bool = True                 # jax.checkpoint each layer (HBM <-> FLOPs)
    # "full" recomputes the whole layer in backward; "save_attn" saves the
    # attention block's output (named checkpoint) so backward recomputes
    # only norms + QKV/FFN matmuls — attention (the expensive recompute:
    # its custom VJP already re-tiles the O(L^2) blocks) runs once
    remat_policy: str = "full"
    logits_softcap: float = 0.0        # tanh soft-capping (0 = off)
    z_loss: float = 0.0                # output z-loss weight
    # blockwise LM-head + cross entropy over C-token chunks (0 = off):
    # avoids materializing the [B, L, V] f32 logits (the largest single
    # train-step buffer); backward recomputes each chunk under remat
    loss_chunk: int = 0

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_heads

    @property
    def hdim(self) -> int:
        return self.head_dim or self.d_model // self.n_heads

    @property
    def ff(self) -> int:
        if self.d_ff is not None:
            return self.d_ff
        if self.mlp == "swiglu":
            # llama-style: 2/3 * 4d rounded up to a multiple of 256 (MXU tiles)
            raw = int(8 * self.d_model / 3)
            return (raw + 255) // 256 * 256
        return 4 * self.d_model

    def __post_init__(self):
        if self.remat_policy not in ("full", "save_attn"):
            raise ValueError(
                f"unknown remat_policy {self.remat_policy!r}; "
                "expected 'full' or 'save_attn'")
        if self.attn_windows is not None:
            if not self.attn_windows or any(
                    not isinstance(w, int) or w < 0
                    for w in self.attn_windows):
                raise ValueError(
                    f"attn_windows must be a non-empty tuple of ints >= 0 "
                    f"(0 = global), got {self.attn_windows!r}")
            if self.n_layers % len(self.attn_windows):
                raise ValueError(
                    f"n_layers {self.n_layers} not divisible by the "
                    f"attn_windows pattern length {len(self.attn_windows)}")

    @property
    def window_pattern(self) -> Tuple[int, ...]:
        """The repeating per-layer window pattern (0 = global)."""
        if self.attn_windows is not None:
            return tuple(self.attn_windows)
        return (self.sliding_window,)

    @property
    def layer_windows(self) -> Tuple[int, ...]:
        """Window per layer, expanded to all n_layers."""
        pat = self.window_pattern
        return pat * (self.n_layers // len(pat))

    @property
    def uniform_window(self) -> int:
        """The single window shared by ALL layers, or 0 when layers mix
        (or no window). Ring KV caches require a uniform window."""
        pat = set(self.window_pattern)
        return self.window_pattern[0] if len(pat) == 1 else 0

    def replace(self, **kw) -> "TransformerConfig":
        return dataclasses.replace(self, **kw)

    def num_params(self) -> int:
        """Parameter count (embeddings included once if tied)."""
        d, f, hd = self.d_model, self.ff, self.hdim
        attn = d * hd * self.n_heads + 2 * d * hd * self.kv_heads + hd * self.n_heads * d
        if self.attn_qkv_bias:
            attn += hd * (self.n_heads + 2 * self.kv_heads)
        if self.mlp == "swiglu":
            mlp = 3 * d * f
        else:
            mlp = 2 * d * f + f + d  # + b_in/b_out biases
        if self.num_experts:
            mlp = mlp * self.num_experts + d * self.num_experts  # + router
        norms = 2 * d
        final_norm = d
        if self.norm == "layer":  # per-norm bias vectors
            norms += 2 * d
            final_norm += d
        per_layer = attn + mlp + norms
        emb = self.vocab_size * d
        head = 0 if self.tie_embeddings else self.vocab_size * d
        pos = self.max_seq_len * d if self.positions == "learned" else 0
        return self.n_layers * per_layer + emb + head + pos + final_norm

    def flops_per_token(self) -> int:
        """Approx training FLOPs/token (fwd+bwd ≈ 6N + attention quadratic)."""
        n = self.num_params()
        emb = self.vocab_size * self.d_model * (1 if self.tie_embeddings else 2)
        return 6 * (n - emb)


# ---------------------------------------------------------------------------
# Presets. llama3_* mirror public Llama-3 shapes; *_debug are CI-sized.
# ---------------------------------------------------------------------------

def llama3_8b() -> TransformerConfig:
    return TransformerConfig(
        vocab_size=128256, d_model=4096, n_layers=32, n_heads=32, n_kv_heads=8,
        d_ff=14336, max_seq_len=8192, mlp="swiglu", norm="rms",
        positions="rope", rope_theta=500000.0,
    )


def llama3_70b() -> TransformerConfig:
    return TransformerConfig(
        vocab_size=128256, d_model=8192, n_layers=80, n_heads=64, n_kv_heads=8,
        d_ff=28672, max_seq_len=8192, mlp="swiglu", norm="rms",
        positions="rope", rope_theta=500000.0,
    )


def llama_1b() -> TransformerConfig:
    """~1.2B params — fits one v5e chip in bf16 with optimizer state sharded."""
    return TransformerConfig(
        vocab_size=32000, d_model=2048, n_layers=16, n_heads=16, n_kv_heads=8,
        d_ff=5632, max_seq_len=4096,
    )


def llama_250m() -> TransformerConfig:
    """~250M-param bench model: large enough that the MXU dominates, small
    enough to init fast on one chip (bench.py's default workload)."""
    return TransformerConfig(
        vocab_size=32000, d_model=1024, n_layers=12, n_heads=16, n_kv_heads=8,
        d_ff=2816, max_seq_len=2048,
    )


def llama_debug() -> TransformerConfig:
    """Tiny config for tests and the multichip dryrun."""
    return TransformerConfig(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=128, remat=False,
    )


def gpt2_small() -> TransformerConfig:
    return TransformerConfig(
        vocab_size=50257, d_model=768, n_layers=12, n_heads=12,
        d_ff=3072, max_seq_len=1024, mlp="gelu", norm="layer",
        positions="learned", tie_embeddings=True,
    )


def gpt2_debug() -> TransformerConfig:
    return TransformerConfig(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4,
        d_ff=256, max_seq_len=128, mlp="gelu", norm="layer",
        positions="learned", tie_embeddings=True, remat=False,
    )


def gemma2_9b() -> TransformerConfig:
    """Gemma-2-9B-family shape: GQA, tied embeddings, tanh softcaps on
    both attention logits (50.0) and output logits (30.0), and the EXACT
    per-layer alternating windows — sliding 4096 on even layers, global on
    odd (HF gemma-2 ``layer_types`` order: layer 0 is sliding). Remaining
    known delta vs the real checkpoint: Gemma-2's pre+post sandwich norms
    are modeled as pre-norms only."""
    return TransformerConfig(
        vocab_size=256128, d_model=3584, n_layers=42, n_heads=16,
        n_kv_heads=8, head_dim=256, d_ff=14336, max_seq_len=8192,
        tie_embeddings=True, logits_softcap=30.0, attn_softcap=50.0,
        attn_windows=(4096, 0),
    )


def gemma_debug() -> TransformerConfig:
    """Tiny gemma-2-style config for tests: alternating windows (local
    layer 0, global layer 1), attention + logits softcaps, GQA, tied
    embeddings."""
    return TransformerConfig(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=128, tie_embeddings=True, logits_softcap=30.0,
        attn_softcap=50.0, attn_windows=(24, 0),
        remat=False,
    )


def mistral_7b() -> TransformerConfig:
    """Mistral-7B-family shape: GQA + 4096-token sliding-window attention."""
    return TransformerConfig(
        vocab_size=32000, d_model=4096, n_layers=32, n_heads=32, n_kv_heads=8,
        d_ff=14336, max_seq_len=8192, sliding_window=4096,
    )


def mistral_debug() -> TransformerConfig:
    """Tiny sliding-window config for tests."""
    return TransformerConfig(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=128, sliding_window=24, remat=False,
    )


def qwen2_7b() -> TransformerConfig:
    """Qwen2-7B-family shape: GQA + q/k/v biases, large vocab, theta 1M.
    Weight-portable via ``models.import_hf`` (exact parity incl. the
    bias path)."""
    return TransformerConfig(
        vocab_size=152064, d_model=3584, n_layers=28, n_heads=28,
        n_kv_heads=4, d_ff=18944, max_seq_len=32768,
        rope_theta=1_000_000.0, norm_eps=1e-6, attn_qkv_bias=True,
    )


def qwen2_debug() -> TransformerConfig:
    """Tiny qwen2-style config for tests: GQA + qkv biases."""
    return TransformerConfig(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=128, attn_qkv_bias=True, remat=False,
    )


def moe_debug() -> TransformerConfig:
    return TransformerConfig(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=4,
        d_ff=128, max_seq_len=128, num_experts=4, expert_top_k=2, remat=False,
    )


PRESETS = {
    "llama3-8b": llama3_8b,
    "llama3-70b": llama3_70b,
    "llama-1b": llama_1b,
    "llama-250m": llama_250m,
    "llama-debug": llama_debug,
    "gpt2-small": gpt2_small,
    "gpt2-debug": gpt2_debug,
    "gemma2-9b": gemma2_9b,
    "gemma-debug": gemma_debug,
    "mistral-7b": mistral_7b,
    "mistral-debug": mistral_debug,
    "qwen2-7b": qwen2_7b,
    "qwen2-debug": qwen2_debug,
    "moe-debug": moe_debug,
}


def get_config(name: str) -> TransformerConfig:
    try:
        return PRESETS[name]()
    except KeyError:
        raise ValueError(f"unknown preset {name!r}; have {sorted(PRESETS)}")
