"""LoRA-style parameter deltas for model multiplexing.

A fine-tune variant in a multiplexed fleet is almost never a full new
weight set — it is a low-rank delta over a shared base (the reference
Serve's model-multiplexing pattern assumes exactly this). A delta here is
a plain pytree of per-layer low-rank factors over named projection
leaves; :func:`apply_delta` materializes only the touched leaves and
SHARES every other leaf with the base, so a resident variant costs the
registry its delta bytes plus the few materialized projections, not a
full model copy.

Pure functions over pytrees like the rest of models/ — no framework
state, cloudpickle/object-store friendly.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.models.config import TransformerConfig

Params = Dict[str, Any]

# default leaves a delta perturbs — attention q/v projections, the classic
# LoRA target set
DEFAULT_TARGETS: Tuple[str, ...] = ("wq", "wv")


def make_delta(rng: jax.Array, config: TransformerConfig, *,
               rank: int = 2, scale: float = 1.0,
               targets: Tuple[str, ...] = DEFAULT_TARGETS) -> Params:
    """Random low-rank delta: per target leaf ``W [L, d, ...]`` the
    factors are ``a [L, d, r]`` and ``b [L, r, prod(rest)]``; the applied
    update is ``scale * (a @ b)`` reshaped to ``W``'s shape. ``scale=0``
    gives an exact-identity variant (useful as a parity fixture)."""
    c = config
    pdt = jnp.dtype(c.param_dtype)
    L, d = c.n_layers, c.d_model
    shapes = {
        "wq": (d, c.n_heads * c.hdim),
        "wk": (d, c.kv_heads * c.hdim),
        "wv": (d, c.kv_heads * c.hdim),
        "wo": (c.n_heads * c.hdim, d),
    }
    out: Dict[str, Any] = {}
    keys = iter(jax.random.split(rng, 2 * max(len(targets), 1)))
    for name in targets:
        if name not in shapes:
            raise ValueError(
                f"unknown delta target {name!r}; have {sorted(shapes)}")
        din, dout = shapes[name]
        a = (jax.random.normal(next(keys), (L, din, rank), jnp.float32)
             * din ** -0.5).astype(pdt)
        b = (jax.random.normal(next(keys), (L, rank, dout), jnp.float32)
             * rank ** -0.5).astype(pdt)
        out[name] = {"a": a, "b": b}
    return {"scale": float(scale), "targets": out}


def apply_delta(params: Params, delta: Params) -> Params:
    """Materialize ``base + delta``: touched layer leaves are rebuilt,
    every other leaf is the SAME array object as the base (zero copy) —
    evicting a variant from a registry never needs to re-fetch the base."""
    scale = float(delta.get("scale", 1.0))
    layers = dict(params["layers"])
    for name, fac in delta["targets"].items():
        w = layers[name]
        flat = w.reshape(w.shape[0], w.shape[1], -1)
        upd = jnp.einsum("ldr,lre->lde", fac["a"].astype(flat.dtype),
                         fac["b"].astype(flat.dtype))
        layers[name] = (flat + scale * upd).reshape(w.shape).astype(w.dtype)
    out = dict(params)
    out["layers"] = layers
    return out


def delta_bytes(delta: Params) -> int:
    """Size of the delta's own factors (what a registry charges a variant
    beyond its base)."""
    total = 0
    for fac in delta["targets"].values():
        for leaf in (fac["a"], fac["b"]):
            total += leaf.size * leaf.dtype.itemsize
    return total


def params_bytes(params: Params) -> int:
    """Total bytes of a param pytree (registry budget accounting)."""
    leaves = jax.tree_util.tree_leaves(params)
    return sum(int(x.size) * x.dtype.itemsize for x in leaves)
