"""Built-in TPU-tuned model family.

The reference has no first-party model zoo (Train wraps user torch models;
RLlib builds small encoders via ``rllib/core/models/``). Here the flagship
LLM family is part of the framework because the headline benchmark is LLM
training on TPU (BASELINE.json north star): decoder-only transformers
covering Llama-3 shapes (RoPE/SwiGLU/RMSNorm/GQA), GPT-2 shapes
(learned-pos/GELU/LayerNorm), and MoE variants, all as pure functions over
param pytrees with logical-axis sharding annotations.
"""

from ray_tpu.models.config import (
    TransformerConfig,
    PRESETS,
    get_config,
    llama3_8b,
    llama3_70b,
    llama_1b,
    llama_250m,
    llama_debug,
    gemma2_9b,
    gemma_debug,
    mistral_7b,
    mistral_debug,
    qwen2_7b,
    qwen2_debug,
    gpt2_small,
    gpt2_debug,
    moe_debug,
)
from ray_tpu.models.transformer import (
    init_params,
    param_axes,
    forward,
    loss_and_metrics,
    init_cache,
    decode_step,
    decode_step_multi,
    init_cache_multi,
    init_cache_paged,
    decode_step_paged,
    verify_step_paged,
    copy_kv_block,
    gather_kv_blocks,
    scatter_kv_blocks,
    generate,
)

from ray_tpu.models.delta import (
    apply_delta,
    delta_bytes,
    make_delta,
    params_bytes,
)

from ray_tpu.models.import_hf import (
    config_from_hf,
    import_hf_llama,
    load_hf_llama,
)

__all__ = [
    "config_from_hf",
    "import_hf_llama",
    "load_hf_llama",
    "TransformerConfig",
    "PRESETS",
    "get_config",
    "llama3_8b",
    "llama3_70b",
    "llama_1b",
    "llama_250m",
    "llama_debug",
    "gemma2_9b",
    "gemma_debug",
    "mistral_7b",
    "mistral_debug",
    "qwen2_7b",
    "qwen2_debug",
    "gpt2_small",
    "gpt2_debug",
    "moe_debug",
    "init_params",
    "param_axes",
    "forward",
    "loss_and_metrics",
    "init_cache",
    "decode_step",
    "decode_step_multi",
    "init_cache_multi",
    "init_cache_paged",
    "decode_step_paged",
    "verify_step_paged",
    "copy_kv_block",
    "gather_kv_blocks",
    "scatter_kv_blocks",
    "generate",
    "apply_delta",
    "delta_bytes",
    "make_delta",
    "params_bytes",
]
