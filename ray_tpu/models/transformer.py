"""Decoder-only transformer: pure-function forward over a param pytree.

TPU-native design notes:

- Parameters are plain pytrees (nested dicts of arrays) with a parallel
  *logical-axes* pytree (:func:`param_axes`); sharding is applied by mapping
  logical names through :mod:`ray_tpu.parallel.sharding` rules — no module
  wrappers (contrast the reference's DDP/FSDP wrapping at
  ``python/ray/train/torch/train_loop_utils.py:158``).
- Layers are **stacked** on a leading dim and the forward runs ``lax.scan``
  over them: one layer gets traced/compiled once regardless of depth, and
  XLA pipelines the weight prefetch of layer i+1 against layer i's compute.
- ``jax.checkpoint`` around the scanned body trades FLOPs for HBM (standard
  remat policy for LLM training).
- Attention dispatches to the Pallas flash kernel on TPU, the blockwise XLA
  kernel elsewhere, and ring attention (``lax.ppermute`` over the ``sp``
  mesh axis) when the ambient mesh has a nontrivial sequence-parallel axis.
- All matmuls run in ``config.dtype`` (bf16 by default) on the MXU; norms,
  softmax, and the loss accumulate in f32.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.models.config import TransformerConfig
from ray_tpu.ops.attention import (_repeat_kv, _softcap_scores,
                                   naive_attention)
from ray_tpu.ops.layers import (apply_rotary, layer_norm, rms_norm,
                                rotary_embedding)
from ray_tpu.ops.moe import moe_layer_dense
from ray_tpu.parallel.sharding import constrain

Params = Dict[str, Any]


# ---------------------------------------------------------------------------
# Init
# ---------------------------------------------------------------------------

def init_params(rng: jax.Array, config: TransformerConfig) -> Params:
    """Initialize a parameter pytree (layers stacked on a leading dim)."""
    c = config
    pdt = jnp.dtype(c.param_dtype)
    d, hd, f, L = c.d_model, c.hdim, c.ff, c.n_layers
    h, kv, v = c.n_heads, c.kv_heads, c.vocab_size

    keys = iter(jax.random.split(rng, 16))

    def normal(key, shape, std):
        return (jax.random.normal(key, shape, jnp.float32) * std).astype(pdt)

    proj_std = d ** -0.5
    out_std = proj_std / (2 * L) ** 0.5  # GPT-2-style depth scaling

    layers: Params = {
        "attn_norm": jnp.ones((L, d), pdt),
        "wq": normal(next(keys), (L, d, h, hd), proj_std),
        "wk": normal(next(keys), (L, d, kv, hd), proj_std),
        "wv": normal(next(keys), (L, d, kv, hd), proj_std),
        "wo": normal(next(keys), (L, h, hd, d), out_std),
        "mlp_norm": jnp.ones((L, d), pdt),
    }
    if c.attn_qkv_bias:
        layers["bq"] = jnp.zeros((L, h, hd), pdt)
        layers["bk"] = jnp.zeros((L, kv, hd), pdt)
        layers["bv"] = jnp.zeros((L, kv, hd), pdt)
    if c.norm == "layer":
        layers["attn_norm_b"] = jnp.zeros((L, d), pdt)
        layers["mlp_norm_b"] = jnp.zeros((L, d), pdt)

    if c.num_experts:
        e = c.num_experts
        layers["router"] = normal(next(keys), (L, d, e), proj_std)
        layers["w_gate"] = normal(next(keys), (L, e, d, f), proj_std)
        layers["w_up"] = normal(next(keys), (L, e, d, f), proj_std)
        layers["w_down"] = normal(next(keys), (L, e, f, d), out_std)
    elif c.mlp == "swiglu":
        layers["w_gate"] = normal(next(keys), (L, d, f), proj_std)
        layers["w_up"] = normal(next(keys), (L, d, f), proj_std)
        layers["w_down"] = normal(next(keys), (L, f, d), out_std)
    else:  # gelu
        layers["w_in"] = normal(next(keys), (L, d, f), proj_std)
        layers["b_in"] = jnp.zeros((L, f), pdt)
        layers["w_out"] = normal(next(keys), (L, f, d), out_std)
        layers["b_out"] = jnp.zeros((L, d), pdt)

    params: Params = {
        "embed": normal(next(keys), (v, d), 0.02),
        "layers": layers,
        "final_norm": jnp.ones((d,), pdt),
    }
    if c.norm == "layer":
        params["final_norm_b"] = jnp.zeros((d,), pdt)
    if c.positions == "learned":
        params["pos_embed"] = normal(next(keys), (c.max_seq_len, d), 0.02)
    if not c.tie_embeddings:
        params["lm_head"] = normal(next(keys), (d, v), proj_std)
    return params


def param_axes(config: TransformerConfig) -> Params:
    """Logical-axes pytree matching :func:`init_params` leaf-for-leaf."""
    c = config
    lay = {
        "attn_norm": ("layers", "norm"),
        "wq": ("layers", "embed", "heads", "head_dim"),
        "wk": ("layers", "embed", "kv_heads", "head_dim"),
        "wv": ("layers", "embed", "kv_heads", "head_dim"),
        "wo": ("layers", "heads", "head_dim", "embed"),
        "mlp_norm": ("layers", "norm"),
    }
    if c.attn_qkv_bias:
        lay["bq"] = ("layers", "heads", "head_dim")
        lay["bk"] = ("layers", "kv_heads", "head_dim")
        lay["bv"] = ("layers", "kv_heads", "head_dim")
    if c.norm == "layer":
        lay["attn_norm_b"] = ("layers", "norm")
        lay["mlp_norm_b"] = ("layers", "norm")
    if c.num_experts:
        lay["router"] = ("layers", "embed", "expert")
        lay["w_gate"] = ("layers", "expert", "embed", "mlp")
        lay["w_up"] = ("layers", "expert", "embed", "mlp")
        lay["w_down"] = ("layers", "expert", "mlp", "embed")
    elif c.mlp == "swiglu":
        lay["w_gate"] = ("layers", "embed", "mlp")
        lay["w_up"] = ("layers", "embed", "mlp")
        lay["w_down"] = ("layers", "mlp", "embed")
    else:
        lay["w_in"] = ("layers", "embed", "mlp")
        lay["b_in"] = ("layers", "mlp")
        lay["w_out"] = ("layers", "mlp", "embed")
        lay["b_out"] = ("layers", "norm")
    axes: Params = {
        "embed": ("vocab", "embed"),
        "layers": lay,
        "final_norm": ("norm",),
    }
    if c.norm == "layer":
        axes["final_norm_b"] = ("norm",)
    if c.positions == "learned":
        axes["pos_embed"] = (None, "embed")
    if not c.tie_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def _qkv_proj(h, lp, dt):
    """q/k/v projections (+ optional Qwen2-style qkv biases)."""
    q = jnp.einsum("bld,dhk->blhk", h, lp["wq"].astype(dt))
    k = jnp.einsum("bld,dhk->blhk", h, lp["wk"].astype(dt))
    v = jnp.einsum("bld,dhk->blhk", h, lp["wv"].astype(dt))
    if "bq" in lp:
        q = q + lp["bq"].astype(dt)
        k = k + lp["bk"].astype(dt)
        v = v + lp["bv"].astype(dt)
    return q, k, v


def _norm(x, w, b, c):
    # Both kinds carry bf16-residual custom VJPs (ops/layers.py) — plain
    # autodiff of the f32 upcast keeps f32 [B, L, D] residuals per site.
    if c.norm == "rms":
        return rms_norm(x, w, eps=c.norm_eps or 1e-6)
    return layer_norm(x, w, b, eps=c.norm_eps or 1e-5)


def _sp_axis_size() -> int:
    """Size of the ambient mesh's sequence-parallel axis (1 if absent)."""
    from jax.sharding import get_abstract_mesh

    mesh = get_abstract_mesh()
    if mesh is None or mesh.empty or "sp" not in mesh.axis_names:
        return 1
    return mesh.shape["sp"]


def _pp_axis_size() -> int:
    """Size of the ambient mesh's pipeline axis (1 if absent)."""
    from jax.sharding import get_abstract_mesh

    mesh = get_abstract_mesh()
    if mesh is None or mesh.empty or "pp" not in mesh.axis_names:
        return 1
    return mesh.shape["pp"]


def _attention(q, k, v, config: TransformerConfig, window: Optional[int] = None):
    """Training attention: ring over sp when sequence-parallel, else flash.

    ``window``: this LAYER's sliding window (per-layer alternation passes
    it explicitly; 0 = global). ``None`` falls back to the config-uniform
    window. Always STATIC — the banded kernels' block liveness is
    compile-time structure.
    """
    if window is None:
        window = config.uniform_window
    sp = _sp_axis_size()
    if sp > 1 and q.shape[1] % sp == 0 and k.shape[1] % sp == 0:
        from jax import shard_map
        from jax.sharding import PartitionSpec as P
        from jax.sharding import get_abstract_mesh

        from ray_tpu.ops.ring_attention import (ring_attention,
                                                sliding_window_attention_sp)

        mesh = get_abstract_mesh()
        batch = tuple(a for a in ("dcn", "dp", "fsdp")
                      if a in mesh.axis_names)
        qspec = P(batch or None, "sp", "tp" if "tp" in mesh.axis_names else None, None)
        if window:
            # windowed + sequence-parallel: halo exchange — ceil(window/
            # Lloc) chained ppermutes, O(window/Lloc) comm independent
            # of sp. Multi-hop handles window > Lloc; any window is
            # exact (hops clamp at sp-1 = all-gather shape).
            inner = functools.partial(sliding_window_attention_sp,
                                      axis="sp",
                                      window=window,
                                      softcap=config.attn_softcap)
        else:
            inner = functools.partial(ring_attention, axis="sp",
                                      causal=True,
                                      softcap=config.attn_softcap)
        fn = shard_map(
            inner,
            mesh=mesh, in_specs=(qspec, qspec, qspec), out_specs=qspec,
            check_vma=False,
        )
        return fn(q, k, v)
    from ray_tpu import config as _knobs
    from ray_tpu.ops.attention import flash_attention, resolve_attention_impl

    # flash_attention carries the memory-efficient custom VJP: O(L)
    # residuals (out + lse) instead of O(L^2) probability blocks — without
    # it the backward of a scanned-layer model OOMs HBM at long context.
    # Tile sizes are config knobs (RTPU_ATTN_BLOCK_Q/K) so on-chip sweeps
    # can tune them without code edits.
    return flash_attention(q, k, v, causal=True,
                           impl=resolve_attention_impl(),
                           q_block=int(_knobs.get("attn_block_q")),
                           kv_block=int(_knobs.get("attn_block_k")),
                           window=window or None,
                           softcap=config.attn_softcap)


def _layers_pipelined(layer_params, x, layer_fn, c, pp, cos, sin):
    """Run the layer stack as a GPipe pipeline over the ``pp`` mesh axis.

    The stacked layer dim is sharded over pp (``"layers": "pp"`` rule), so
    each stage holds L/pp layers; activations rotate stage-to-stage inside
    :func:`ray_tpu.train.pipeline.pipeline_apply` (``lax.ppermute`` over
    ICI). ``shard_map`` is manual ONLY over pp (``axis_names={"pp"}``) —
    fsdp/tp shardings inside each block stay GSPMD-auto, so pp composes
    with the other axes. MoE layers are excluded (their aux-loss carry
    doesn't thread through the pipeline state; use ep for MoE scale-out).
    Pipeline parallel is absent from the reference (SURVEY §2.4).
    """
    from jax.sharding import PartitionSpec as P, get_abstract_mesh

    from ray_tpu.train.pipeline import (merge_microbatches, pipeline_apply,
                                        split_microbatches)

    if c.num_experts:
        raise NotImplementedError(
            "pipeline parallelism excludes MoE layers (aux loss does not "
            "thread through the pipeline carry); shard experts over ep")
    num_micro = c.pp_microbatches or 2 * pp
    b = x.shape[0]
    if b % num_micro:
        raise ValueError(
            f"batch {b} not divisible by pp microbatches {num_micro}")
    micro = split_microbatches(x, num_micro)  # [M, mb, L, D]
    lspecs = jax.tree.map(lambda _: P("pp"), layer_params)
    # rope tables ride as explicit replicated args (shard_map must not
    # close over traced arrays)
    extras = () if cos is None else (cos, sin)
    especs = () if cos is None else (P(), P())

    def run(lps, m, *extra):
        cs, sn = (extra + (None, None))[:2]

        def block(lp, h):
            h2, _aux = layer_fn(h, lp, cs, sn)
            return h2

        blk = _remat_wrap(block, c)
        return pipeline_apply(blk, lps, m, axis="pp")

    out = jax.shard_map(
        run,
        mesh=get_abstract_mesh(),
        in_specs=(lspecs, P()) + especs,
        out_specs=P(),
        axis_names={"pp"},
        # VMA checking off: scans INSIDE the stage compute (blockwise
        # attention) init fresh zeros (unvarying) and combine them with
        # pp-varying activations, which the checker rejects at every such
        # site; replication of the final output holds by construction
        # (pipeline_apply broadcasts the last stage's result)
        check_vma=False,
    )(layer_params, micro, *extras)
    return merge_microbatches(out), jnp.zeros((), jnp.float32)


def _remat_wrap(layer_fn, c: "TransformerConfig"):
    """Apply the config's rematerialization choice to the layer body.

    ``remat_policy="save_attn"`` keeps the named ``attn_out`` residual
    (bf16 [B,L,H,K] per layer) so the backward pass recomputes norms and
    matmuls but NOT attention — attention recompute is the costly part
    (the flash custom VJP re-tiles O(L^2) blocks a second time under full
    remat)."""
    if not c.remat:
        return layer_fn
    if c.remat_policy == "save_attn":
        policy = jax.checkpoint_policies.save_only_these_names("attn_out")
        return jax.checkpoint(layer_fn, policy=policy)
    return jax.checkpoint(layer_fn)


def forward_features(
    params: Params,
    tokens: jax.Array,
    config: TransformerConfig,
    *,
    positions: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Transformer stack up to (and including) the final norm:
    tokens [B, L] int32 → (features [B, L, D], moe_aux). The LM head is
    applied by :func:`forward` — split out so the chunked-loss path can
    run head+softmax blockwise without materializing [B, L, V] logits."""
    c = config
    dt = jnp.dtype(c.dtype)
    b, l = tokens.shape
    if positions is None:
        positions = jnp.arange(l)[None, :]

    # Embedding lookup. STORAGE is (vocab:tp, embed:fsdp) — ZeRO-3 — but
    # the lookup runs against a (vocab:tp, replicated-D) view: a D:fsdp
    # gather output cannot be resharded to (batch, seq) activation layout
    # without the SPMD partitioner's involuntary full rematerialization
    # (the MULTICHIP warnings); all-gathering the table's D axis first is
    # one clean collective and the standard TPU embedding layout.
    tbl = constrain(params["embed"].astype(dt), ("vocab", None))
    x = tbl[tokens]
    if c.positions == "learned":
        x = x + params["pos_embed"].astype(dt)[positions[0]][None]
    x = constrain(x, ("batch", "seq", None))

    if c.positions == "rope":
        cos, sin = rotary_embedding(positions[0], c.hdim, theta=c.rope_theta)
    else:
        cos = sin = None

    def layer(x, lp, cos=cos, sin=sin, window=None):
        h = _norm(x, lp["attn_norm"], lp.get("attn_norm_b"), c)
        q, k, v = _qkv_proj(h, lp, dt)
        if cos is not None:
            q = apply_rotary(q, cos, sin)
            k = apply_rotary(k, cos, sin)
        q = constrain(q, ("batch", "seq", "heads", None))
        k = constrain(k, ("batch", "seq", "kv_heads", None))
        o = _attention(q, k, v, c, window=window)
        from jax.ad_checkpoint import checkpoint_name

        o = checkpoint_name(o, "attn_out")  # no-op unless a policy saves it
        o = jnp.einsum("blhk,hkd->bld", o, lp["wo"].astype(dt))
        x = constrain(x + o, ("batch", "seq", None))

        h = _norm(x, lp["mlp_norm"], lp.get("mlp_norm_b"), c)
        aux = jnp.zeros((), jnp.float32)
        if c.num_experts:
            m, aux = moe_layer_dense(
                h, lp["router"].astype(dt), lp["w_gate"].astype(dt),
                lp["w_up"].astype(dt), lp["w_down"].astype(dt),
                k=c.expert_top_k, capacity_factor=c.expert_capacity_factor,
            )
        elif c.mlp == "swiglu":
            g = jax.nn.silu(jnp.einsum("bld,df->blf", h, lp["w_gate"].astype(dt)))
            u = jnp.einsum("bld,df->blf", h, lp["w_up"].astype(dt))
            gu = constrain(g * u, ("batch", "seq", "mlp"))
            m = jnp.einsum("blf,fd->bld", gu, lp["w_down"].astype(dt))
        else:
            hmid = jnp.einsum("bld,df->blf", h, lp["w_in"].astype(dt))
            hmid = jax.nn.gelu(hmid + lp["b_in"].astype(dt))
            hmid = constrain(hmid, ("batch", "seq", "mlp"))
            m = jnp.einsum("blf,fd->bld", hmid, lp["w_out"].astype(dt))
            m = m + lp["b_out"].astype(dt)
        x = constrain(x + m, ("batch", "seq", None))
        return x, aux

    pattern = c.window_pattern
    uniform = len(set(pattern)) == 1

    pp = _pp_axis_size()
    if pp > 1:
        if not uniform:
            raise NotImplementedError(
                "per-layer alternating windows (attn_windows) are not "
                "supported with pipeline parallelism yet; use a uniform "
                "window or pp=1")
        x, moe_aux = _layers_pipelined(params["layers"], x, layer, c, pp,
                                       cos, sin)
    elif uniform:
        body = _remat_wrap(layer, c)

        def scan_step(carry, lp):
            x, aux_sum = carry
            x, aux = body(x, lp)
            return (x, aux_sum + aux), None

        (x, moe_aux), _ = lax.scan(scan_step,
                                   (x, jnp.zeros((), jnp.float32)),
                                   params["layers"])
    else:
        # Per-layer alternating windows (Gemma-2): scan layer GROUPS of
        # the pattern length, each sub-layer compiled with its own STATIC
        # window — the banded kernels' block liveness is compile-time
        # structure, so a traced per-layer window is not an option. Same
        # one-compilation scan economy: the group body traces P layers
        # once, not n_layers times.
        P_ = len(pattern)
        n_groups = c.n_layers // P_
        grouped = jax.tree.map(
            lambda a: a.reshape((n_groups, P_) + a.shape[1:]),
            params["layers"])
        bodies = [_remat_wrap(functools.partial(layer, window=w), c)
                  for w in pattern]

        def scan_group(carry, glp):
            x, aux_sum = carry
            for i in range(P_):
                lp_i = jax.tree.map(lambda a: a[i], glp)
                x, aux = bodies[i](x, lp_i)
                aux_sum = aux_sum + aux
            return (x, aux_sum), None

        (x, moe_aux), _ = lax.scan(scan_group,
                                   (x, jnp.zeros((), jnp.float32)),
                                   grouped)

    x = _norm(x, params["final_norm"], params.get("final_norm_b"), c)
    return x, moe_aux


def forward(
    params: Params,
    tokens: jax.Array,
    config: TransformerConfig,
    *,
    positions: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Full-sequence forward. tokens: [B, L] int32 → (logits [B,L,V] f32, moe_aux)."""
    c = config
    x, moe_aux = forward_features(params, tokens, c, positions=positions)
    logits = jnp.einsum("bld,dv->blv", x, _lm_head(params, c)).astype(
        jnp.float32)
    if c.logits_softcap:
        logits = jnp.tanh(logits / c.logits_softcap) * c.logits_softcap
    return logits, moe_aux


def _lm_head(params: Params, c: TransformerConfig) -> jax.Array:
    dt = jnp.dtype(c.dtype)
    return (params["embed"].T if c.tie_embeddings
            else params["lm_head"]).astype(dt)


# ---------------------------------------------------------------------------
# Loss
# ---------------------------------------------------------------------------

def loss_and_metrics(
    params: Params,
    batch: Dict[str, jax.Array],
    config: TransformerConfig,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Next-token cross entropy. batch: {"tokens": [B,L]} or explicit
    {"inputs", "targets", "mask"}."""
    if "inputs" in batch:
        inputs, targets = batch["inputs"], batch["targets"]
        mask = batch.get("mask")
    else:
        toks = batch["tokens"]
        inputs, targets = toks[:, :-1], toks[:, 1:]
        mask = batch.get("mask")
        if mask is not None:
            mask = mask[:, 1:]
    if mask is None:
        mask = jnp.ones(targets.shape, jnp.float32)
    mask = mask.astype(jnp.float32)

    C = config.loss_chunk
    if C and targets.shape[1] > C:
        nll_sum, z_sum, moe_aux = _chunked_xent(params, inputs, targets,
                                                mask, config)
    else:
        logits, moe_aux = forward(params, inputs, config)
        logz = jax.nn.logsumexp(logits, axis=-1)
        tgt_logit = jnp.take_along_axis(
            logits, targets[..., None], axis=-1)[..., 0]
        nll_sum = ((logz - tgt_logit) * mask).sum()
        z_sum = ((logz ** 2) * mask).sum() if config.z_loss else None
    denom = jnp.maximum(mask.sum(), 1.0)
    loss = nll_sum / denom
    metrics = {"loss": loss, "ntokens": mask.sum()}
    if config.z_loss:
        zl = config.z_loss * z_sum / denom
        loss = loss + zl
        metrics["z_loss"] = zl
    if config.num_experts:
        loss = loss + config.moe_aux_weight * moe_aux
        metrics["moe_aux"] = moe_aux
    metrics["perplexity"] = jnp.exp(jnp.minimum(metrics["loss"], 20.0))
    return loss, metrics


def _chunked_xent(params, inputs, targets, mask, c: TransformerConfig):
    """Blockwise LM-head + cross entropy over sequence chunks.

    The full [B, L, V] f32 logits tensor is the largest single buffer in
    a train step (batch 16 x 2048 x 32000 = 4.2 GB, doubled by its
    cotangent). Applying head+softmax per C-token chunk under
    ``jax.checkpoint`` keeps only [B, C, V] live at a time — backward
    recomputes each chunk's logits from the (cheap-to-keep) features.
    Classic memory-efficient CE; no reference counterpart (torch keeps
    full logits). Sequences that don't divide by the chunk are padded with
    mask-0 positions (never a silent dense fallback — that would
    reintroduce the multi-GB buffer exactly when the user asked to avoid
    it)."""
    x, moe_aux = forward_features(params, inputs, c)
    head = _lm_head(params, c)
    pad = (-targets.shape[1]) % c.loss_chunk
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
        targets = jnp.pad(targets, ((0, 0), (0, pad)))
        mask = jnp.pad(mask, ((0, 0), (0, pad)))
    b, l, d = x.shape
    n = l // c.loss_chunk
    want_z = bool(c.z_loss)

    def chunk(args):
        xc, tc, mc = args  # [B, C, D], [B, C], [B, C]
        logits = jnp.einsum("bcd,dv->bcv", xc, head).astype(jnp.float32)
        if c.logits_softcap:
            logits = jnp.tanh(logits / c.logits_softcap) * c.logits_softcap
        logz = jax.nn.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, tc[..., None], axis=-1)[..., 0]
        nll = ((logz - tgt) * mc).sum()
        return (nll, ((logz ** 2) * mc).sum()) if want_z else nll

    xs = x.reshape(b, n, c.loss_chunk, d).swapaxes(0, 1)
    ts = targets.reshape(b, n, c.loss_chunk).swapaxes(0, 1)
    ms = mask.reshape(b, n, c.loss_chunk).swapaxes(0, 1)
    out = jax.lax.map(jax.checkpoint(chunk), (xs, ts, ms))
    if want_z:
        return out[0].sum(), out[1].sum(), moe_aux
    return out.sum(), None, moe_aux


# ---------------------------------------------------------------------------
# KV-cache decode (serve / RL inference path)
# ---------------------------------------------------------------------------

def init_cache(config: TransformerConfig, batch: int, max_len: int,
               dtype=None, rolling: Optional[bool] = None) -> Params:
    """KV cache. With ``sliding_window`` set and smaller than ``max_len``,
    the cache is a RING of ``sliding_window`` slots (Mistral-style): HBM
    stays O(window) no matter how long generation runs — the serving
    memory win SWA exists for. ``rolling=False`` forces the full-length
    layout (needed when a single prefill chunk exceeds the window)."""
    c = config
    dt = jnp.dtype(dtype or c.dtype)
    # ring layout requires ONE window shared by all layers (the cache is a
    # single [n_layers, ...] stack); per-layer alternating windows with a
    # global layer anywhere force the full-length layout
    uniform = c.uniform_window
    if rolling and not uniform:
        raise ValueError(
            "ring KV layout requires ONE window shared by all layers; "
            f"this config's pattern is {c.window_pattern} (0 = global / "
            "mixed) — use rolling=False (full-length cache)")
    use_ring = (bool(uniform) and uniform < max_len
                if rolling is None else rolling)
    length = uniform if use_ring else max_len
    shape = (c.n_layers, batch, length, c.kv_heads, c.hdim)
    return {
        "k": jnp.zeros(shape, dt),
        "v": jnp.zeros(shape, dt),
        "pos": jnp.zeros((), jnp.int32),
    }


def decode_step(
    params: Params,
    cache: Params,
    tokens: jax.Array,
    config: TransformerConfig,
) -> Tuple[jax.Array, Params]:
    """Append ``tokens`` [B, T] (prompt chunk or single step) to the cache and
    return (logits [B, T, V], new cache). Static T → one compiled program per
    chunk length (prefill vs decode=1)."""
    c = config
    dt = jnp.dtype(c.dtype)
    b, t = tokens.shape
    pos0 = cache["pos"]
    positions = pos0 + jnp.arange(t)
    cache_len = cache["k"].shape[2]
    # ring layout iff the cache was allocated at exactly the window size
    # (init_cache's rolling mode); slots are kept oldest->newest by
    # rolling, so slot j holds absolute position pos_new - cache_len + j
    uniform = c.uniform_window
    is_ring = bool(uniform) and cache_len == uniform
    # per-layer effective windows for the masked full-cache path (traced
    # through the layer scan; 2^30 = "global" — far beyond any position)
    win_arr = jnp.array([w if w > 0 else (1 << 30)
                         for w in c.layer_windows], jnp.int32)
    if is_ring and t > cache_len:
        raise ValueError(
            f"prefill chunk {t} exceeds the ring cache ({cache_len}); "
            "feed the prompt in <=window chunks or init_cache(..., "
            "rolling=False)")

    x = params["embed"].astype(dt)[tokens]
    if c.positions == "learned":
        x = x + jnp.take(params["pos_embed"].astype(dt), positions, axis=0)[None]
    if c.positions == "rope":
        cos, sin = rotary_embedding(positions, c.hdim, theta=c.rope_theta)
    else:
        cos = sin = None

    def layer(carry, inp):
        x = carry
        lp, kc, vc, wl = inp
        h = _norm(x, lp["attn_norm"], lp.get("attn_norm_b"), c)
        q, k, v = _qkv_proj(h, lp, dt)
        if cos is not None:
            q = apply_rotary(q, cos, sin)
            k = apply_rotary(k, cos, sin)
        if is_ring:
            # MODULAR ring layout everywhere: position p lives in slot
            # p % W; slot s holds the largest p ≡ s (mod W) written so
            # far (negative = unfilled). Keys are stored already-rotated
            # at absolute positions, and softmax is permutation-invariant
            # over keys, so only the MASK needs positions — which
            # naive_attention takes per-slot via ``k_positions``.
            if t == 1:
                # hot decode loop: ONE slot write, no roll/concat copies.
                # The overwritten slot held pos0 - W — out-of-window for
                # this query — so writing before attending is safe.
                slot = pos0 % cache_len
                kc = lax.dynamic_update_slice(kc, k.astype(kc.dtype),
                                              (0, slot, 0, 0))
                vc = lax.dynamic_update_slice(vc, v.astype(vc.dtype),
                                              (0, slot, 0, 0))
                slot_pos = pos0 - (
                    (slot - jnp.arange(cache_len)) % cache_len)
                o = naive_attention(q, kc, vc, causal=True, q_offset=pos0,
                                    window=uniform,
                                    k_positions=slot_pos,
                                    softcap=c.attn_softcap)
            else:
                # chunked prefill: attend over old ring ++ new keys
                # BEFORE evicting — a key evicted by the END of this
                # chunk can still be in-window for its EARLY queries
                prev = pos0 - 1
                slot_pos_old = prev - (
                    ((prev % cache_len) - jnp.arange(cache_len))
                    % cache_len)
                k_all = jnp.concatenate([kc, k.astype(kc.dtype)], axis=1)
                v_all = jnp.concatenate([vc, v.astype(vc.dtype)], axis=1)
                pos_all = jnp.concatenate([slot_pos_old, positions])
                o = naive_attention(q, k_all, v_all, causal=True,
                                    q_offset=pos0,
                                    window=uniform,
                                    k_positions=pos_all,
                                    softcap=c.attn_softcap)
                idx = positions % cache_len
                kc = kc.at[:, idx].set(k.astype(kc.dtype))
                vc = vc.at[:, idx].set(v.astype(vc.dtype))
        else:
            kc = lax.dynamic_update_slice(kc, k.astype(kc.dtype),
                                          (0, pos0, 0, 0))
            vc = lax.dynamic_update_slice(vc, v.astype(vc.dtype),
                                          (0, pos0, 0, 0))
            # wl is this layer's window riding the scan (2^30 = global),
            # so alternating-window models decode exactly
            o = naive_attention(q, kc, vc, causal=True, q_offset=pos0,
                                window=wl, softcap=c.attn_softcap)
        o = jnp.einsum("blhk,hkd->bld", o, lp["wo"].astype(dt))
        x = x + o
        return _decode_mlp(x, lp, c, dt), (kc, vc)

    x, (new_k, new_v) = lax.scan(
        layer, x, (params["layers"], cache["k"], cache["v"], win_arr)
    )
    x = _norm(x, params["final_norm"], params.get("final_norm_b"), c)
    head = (params["embed"].T if c.tie_embeddings else params["lm_head"]).astype(dt)
    logits = jnp.einsum("bld,dv->blv", x, head).astype(jnp.float32)
    new_cache = {"k": new_k, "v": new_v, "pos": pos0 + t}
    return logits, new_cache



def _decode_mlp(x, lp, c, dt):
    """Post-attention norm + MLP tail shared by the decode paths (the ONE
    definition — decode_step and decode_step_multi must never diverge)."""
    h = _norm(x, lp["mlp_norm"], lp.get("mlp_norm_b"), c)
    if c.num_experts:
        m, _ = moe_layer_dense(
            h, lp["router"].astype(dt), lp["w_gate"].astype(dt),
            lp["w_up"].astype(dt), lp["w_down"].astype(dt),
            k=c.expert_top_k, capacity_factor=c.expert_capacity_factor,
        )
    elif c.mlp == "swiglu":
        g = jax.nn.silu(jnp.einsum("bld,df->blf", h, lp["w_gate"].astype(dt)))
        m = jnp.einsum("blf,fd->bld", g * jnp.einsum(
            "bld,df->blf", h, lp["w_up"].astype(dt)), lp["w_down"].astype(dt))
    else:
        hmid = jax.nn.gelu(jnp.einsum(
            "bld,df->blf", h, lp["w_in"].astype(dt)) + lp["b_in"].astype(dt))
        m = jnp.einsum("blf,fd->bld", hmid, lp["w_out"].astype(dt))
        m = m + lp["b_out"].astype(dt)
    return x + m


def init_cache_multi(config: TransformerConfig, n_slots: int,
                     max_len: int, dtype=None) -> Params:
    """Per-sample-position KV cache for :func:`decode_step_multi`
    (continuous batching): like :func:`init_cache` with ``rolling=False``
    but ``pos`` is a [n_slots] vector — each slot is an independent
    request at its own depth. Always full-length layout (ring layouts
    need one shared window AND one shared position)."""
    c = config
    dt = jnp.dtype(dtype or c.dtype)
    shape = (c.n_layers, n_slots, max_len, c.kv_heads, c.hdim)
    return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt),
            "pos": jnp.zeros((n_slots,), jnp.int32)}


def decode_step_multi(
    params: Params,
    cache: Params,
    tokens: jax.Array,
    config: TransformerConfig,
    active: Optional[jax.Array] = None,
) -> Tuple[jax.Array, Params]:
    """One decode step for B independent sequences at PER-SAMPLE positions
    — the continuous-batching inner step (slot b is its own request, mid-
    generation at its own depth). tokens: [B, 1] int32; ``cache["pos"]``:
    [B] int32 (contrast :func:`decode_step`'s single scalar). Rows where
    ``active`` is False keep cache and position unchanged (parked slots).
    Requires the full-length cache layout (``init_cache(...,
    rolling=False)``-style); per-layer alternating windows are honored
    via the same traced window array as :func:`decode_step`. Returns
    (logits [B, V] of each row's newest token, new cache).

    Reference role: Serve's batching/streaming pieces
    (``python/ray/serve/batching.py``) joined with an LLM decode loop —
    the reference has no LLM engine; this is the TPU-first
    differentiator (one jitted step, static [B_slots] shapes).
    """
    c = config
    dt = jnp.dtype(c.dtype)
    b = tokens.shape[0]
    pos = cache["pos"]                      # [B]
    cache_len = cache["k"].shape[2]
    if active is None:
        active = jnp.ones((b,), bool)
    win_arr = jnp.array([w if w > 0 else (1 << 30)
                         for w in c.layer_windows], jnp.int32)

    x = params["embed"].astype(dt)[tokens[:, 0]][:, None]      # [B, 1, D]
    if c.positions == "learned":
        x = x + jnp.take(params["pos_embed"].astype(dt), pos,
                         axis=0)[:, None]
    if c.positions == "rope":
        cos, sin = rotary_embedding(pos[:, None], c.hdim,
                                    theta=c.rope_theta)        # [B, 1, D/2]
    else:
        cos = sin = None

    rows = jnp.arange(b)
    kpos = jnp.arange(cache_len)[None, :]                      # [1, len]
    sel = active[:, None, None, None]

    def layer(carry, inp):
        x = carry
        lp, kc, vc, wl = inp
        h = _norm(x, lp["attn_norm"], lp.get("attn_norm_b"), c)
        q, k, v = _qkv_proj(h, lp, dt)
        if cos is not None:
            q = apply_rotary(q, cos, sin)
            k = apply_rotary(k, cos, sin)
        # per-sample slot write, masked so parked rows keep their cache
        kc = jnp.where(sel, kc.at[rows, pos].set(k[:, 0]), kc)
        vc = jnp.where(sel, vc.at[rows, pos].set(v[:, 0]), vc)
        # one-query attention over the whole slot cache, per-sample band
        kx = _repeat_kv(kc, c.n_heads)
        vx = _repeat_kv(vc, c.n_heads)
        s = jnp.einsum("bhd,bkhd->bhk", q[:, 0].astype(jnp.float32),
                       kx.astype(jnp.float32)) * (c.hdim ** -0.5)
        s = _softcap_scores(s, c.attn_softcap)
        vis = (kpos <= pos[:, None]) & (kpos > pos[:, None] - wl)
        s = jnp.where(vis[:, None, :], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhk,bkhd->bhd", p,
                       vx.astype(jnp.float32)).astype(dt)[:, None]
        o = jnp.einsum("blhk,hkd->bld", o, lp["wo"].astype(dt))
        x = x + o
        return _decode_mlp(x, lp, c, dt), (kc, vc)

    x, (new_k, new_v) = lax.scan(
        layer, x, (params["layers"], cache["k"], cache["v"], win_arr))
    x = _norm(x, params["final_norm"], params.get("final_norm_b"), c)
    head = (params["embed"].T if c.tie_embeddings
            else params["lm_head"]).astype(dt)
    logits = jnp.einsum("bld,dv->blv", x, head).astype(jnp.float32)[:, 0]
    if c.logits_softcap:
        logits = jnp.tanh(logits / c.logits_softcap) * c.logits_softcap
    new_cache = {"k": new_k, "v": new_v,
                 "pos": pos + active.astype(jnp.int32)}
    return logits, new_cache


def init_cache_paged(config: TransformerConfig, num_blocks: int,
                     block_size: int, dtype=None) -> Params:
    """Block-paged KV cache for :func:`decode_step_paged` (the serving
    tier's vLLM-style layout): physical storage is a pool of fixed-size
    token blocks shared by EVERY request; each request maps its logical
    positions onto physical blocks through a per-slot block table. No
    per-slot ``pos`` lives here — positions and block ownership are
    host-side scheduler state (``ray_tpu.serve.kv_cache``), which is what
    makes prefix sharing possible: two requests whose tables name the
    same immutable block read the same HBM."""
    c = config
    dt = jnp.dtype(dtype or c.dtype)
    shape = (c.n_layers, num_blocks, block_size, c.kv_heads, c.hdim)
    return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}


def copy_kv_block(cache: Params, src, dst) -> Params:
    """Copy one physical block (all layers) — the device half of
    copy-on-write: when a request must write into a block whose refcount
    is > 1 (shared prefix tail), the pool duplicates it first so the
    sharers keep reading the original."""
    return {"k": cache["k"].at[:, dst].set(cache["k"][:, src]),
            "v": cache["v"].at[:, dst].set(cache["v"][:, src])}


def gather_kv_blocks(cache: Params, block_ids) -> Params:
    """Gather a request's physical blocks out of the paged pool — the
    device half of KV-block EXPORT for disaggregated prefill/decode:
    the prefill engine pulls exactly the blocks named by one request's
    table ([L, n, bs, kvh, hd] per tensor) without ever materializing
    the whole pool on the host. The result is contiguous, so the
    transfer plane ships it as one raw tensor body."""
    ids = jnp.asarray(block_ids, jnp.int32)
    return {"k": cache["k"][:, ids], "v": cache["v"][:, ids]}


def scatter_kv_blocks(cache: Params, block_ids, kv: Params) -> Params:
    """Scatter a shipped block batch into this pool's physical blocks —
    the device half of KV-block ADOPTION on a decode engine: the blocks
    claimed for the arriving request (and ONLY those rows) are
    overwritten with the prefill engine's exported KV. ``kv`` layout
    matches :func:`gather_kv_blocks` ([L, n, bs, kvh, hd]). Out-of-range
    ids are DROPPED (mode="drop") — the engine pads batches to bucketed
    shapes with the out-of-range id so one compile serves a bucket of
    block counts instead of retracing per count."""
    ids = jnp.asarray(block_ids, jnp.int32)
    return {"k": cache["k"].at[:, ids].set(kv["k"].astype(cache["k"].dtype),
                                           mode="drop"),
            "v": cache["v"].at[:, ids].set(kv["v"].astype(cache["v"].dtype),
                                           mode="drop")}


def decode_step_paged(
    params: Params,
    cache: Params,
    tokens: jax.Array,
    block_tables: jax.Array,
    pos: jax.Array,
    nvalid: jax.Array,
    config: TransformerConfig,
    active: Optional[jax.Array] = None,
) -> Tuple[jax.Array, Params]:
    """Advance B independent requests by up to C tokens each against the
    block-paged cache — ONE compiled program serves both chunked prefill
    (rows feeding C prompt tokens) and decode (rows feeding 1 token with
    C-1 padding), so a long prompt never stalls the in-flight decodes
    sharing its batch.

    tokens: [B, C] int32; block_tables: [B, M] int32 physical block ids
    (row-major: logical position p of request b lives in physical block
    ``block_tables[b, p // bs]`` at offset ``p % bs``; unused entries must
    hold a valid id — they are masked, never written). pos: [B] tokens
    already cached; nvalid: [B] how many of this step's C tokens are real.
    Writes land via an out-of-bounds-dropped scatter, so invalid rows and
    padding touch nothing (a shared prefix block is immutable because no
    live request's write positions ever map into it). Returns (logits
    [B, V] of each row's LAST VALID token, new cache)."""
    return _step_paged_impl(params, cache, tokens, block_tables, pos,
                            nvalid, config, active, all_logits=False)


def verify_step_paged(
    params: Params,
    cache: Params,
    tokens: jax.Array,
    block_tables: jax.Array,
    pos: jax.Array,
    nvalid: jax.Array,
    config: TransformerConfig,
    active: Optional[jax.Array] = None,
) -> Tuple[jax.Array, Params]:
    """The speculative-decoding verify twin of :func:`decode_step_paged`:
    identical cache semantics and masking, but logits come back for EVERY
    fed position ([B, C, V]) instead of only each row's last valid one.
    Feeding ``[last, d1..dk]`` verifies a k-token draft in one call —
    logits[:, i] is the target's distribution after consuming input i, so
    the greedy accept check is a per-position argmax compare. Invalid
    positions still write nothing; their logits are garbage and must be
    masked host-side via ``nvalid``. The extra lm-head cost (B*C rows vs
    B) is the price of batched verification and is exactly what the
    draft's accepted tokens amortize."""
    return _step_paged_impl(params, cache, tokens, block_tables, pos,
                            nvalid, config, active, all_logits=True)


def _step_paged_impl(
    params: Params,
    cache: Params,
    tokens: jax.Array,
    block_tables: jax.Array,
    pos: jax.Array,
    nvalid: jax.Array,
    config: TransformerConfig,
    active: Optional[jax.Array] = None,
    *,
    all_logits: bool = False,
) -> Tuple[jax.Array, Params]:
    c = config
    dt = jnp.dtype(c.dtype)
    b, t = tokens.shape
    n_blocks, bs = cache["k"].shape[1], cache["k"].shape[2]
    m = block_tables.shape[1]
    if active is None:
        active = jnp.ones((b,), bool)
    win_arr = jnp.array([w if w > 0 else (1 << 30)
                         for w in c.layer_windows], jnp.int32)

    positions = pos[:, None] + jnp.arange(t)[None, :]           # [B, C]
    valid = (jnp.arange(t)[None, :] < nvalid[:, None]) \
        & active[:, None]                                       # [B, C]
    # physical destination of each new token; invalid -> OOB (dropped)
    blk = jnp.take_along_axis(block_tables,
                              jnp.clip(positions // bs, 0, m - 1), axis=1)
    dest = jnp.where(valid, blk * bs + positions % bs,
                     n_blocks * bs).reshape(-1)                 # [B*C]
    # gather map: logical position j of request b = physical row gidx[b,j]
    gidx = (block_tables[:, :, None] * bs
            + jnp.arange(bs)[None, None, :]).reshape(b, m * bs)

    x = params["embed"].astype(dt)[tokens]                      # [B, C, D]
    if c.positions == "learned":
        # clamp ONLY the table lookup (padding rows can sit past the
        # table); rope below uses the true positions — the dense decode
        # paths do, and clamping would skew angles past max_seq_len
        x = x + jnp.take(params["pos_embed"].astype(dt),
                         jnp.clip(positions, 0, c.max_seq_len - 1), axis=0)
    if c.positions == "rope":
        cos, sin = rotary_embedding(positions, c.hdim,
                                    theta=c.rope_theta)     # [B, C, D/2]
    else:
        cos = sin = None

    kpos = jnp.arange(m * bs)[None, None, :]                # [1, 1, Mbs]

    def layer(carry, inp):
        x = carry
        lp, kc, vc, wl = inp
        h = _norm(x, lp["attn_norm"], lp.get("attn_norm_b"), c)
        q, k, v = _qkv_proj(h, lp, dt)
        if cos is not None:
            q = apply_rotary(q, cos, sin)
            k = apply_rotary(k, cos, sin)
        # write BEFORE gathering: queries at chunk offset c must see the
        # chunk's own earlier keys (in-chunk causal self-attention)
        kcf = kc.reshape(n_blocks * bs, *kc.shape[2:])
        vcf = vc.reshape(n_blocks * bs, *vc.shape[2:])
        kcf = kcf.at[dest].set(k.reshape(b * t, *k.shape[2:])
                               .astype(kcf.dtype), mode="drop")
        vcf = vcf.at[dest].set(v.reshape(b * t, *v.shape[2:])
                               .astype(vcf.dtype), mode="drop")
        kctx = kcf[gidx]                            # [B, Mbs, kvh, hd]
        vctx = vcf[gidx]
        kx = _repeat_kv(kctx, c.n_heads)
        vx = _repeat_kv(vctx, c.n_heads)
        s = jnp.einsum("bchd,bkhd->bhck", q.astype(jnp.float32),
                       kx.astype(jnp.float32)) * (c.hdim ** -0.5)
        s = _softcap_scores(s, c.attn_softcap)
        vis = (kpos <= positions[:, :, None]) \
            & (kpos > positions[:, :, None] - wl)       # [B, C, Mbs]
        s = jnp.where(vis[:, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhck,bkhd->bchd", p,
                       vx.astype(jnp.float32)).astype(dt)
        o = jnp.einsum("blhk,hkd->bld", o, lp["wo"].astype(dt))
        x = x + o
        return _decode_mlp(x, lp, c, dt), (
            kcf.reshape(kc.shape), vcf.reshape(vc.shape))

    x, (new_k, new_v) = lax.scan(
        layer, x, (params["layers"], cache["k"], cache["v"], win_arr))
    x = _norm(x, params["final_norm"], params.get("final_norm_b"), c)
    head = (params["embed"].T if c.tie_embeddings
            else params["lm_head"]).astype(dt)
    if all_logits:
        # verify path: the accept check needs a distribution at every fed
        # position, so project all B*C rows
        logits = jnp.einsum("bcd,dv->bcv", x, head).astype(jnp.float32)
    else:
        # only each row's LAST VALID position needs logits — project D->V
        # for B rows, not B*C (the lm-head matmul dominates small-model
        # steps)
        last = jnp.clip(nvalid - 1, 0, t - 1)
        x_last = jnp.take_along_axis(x, last[:, None, None], axis=1)[:, 0]
        logits = jnp.einsum("bd,dv->bv", x_last, head).astype(jnp.float32)
    if c.logits_softcap:
        logits = jnp.tanh(logits / c.logits_softcap) * c.logits_softcap
    return logits, {"k": new_k, "v": new_v}


def generate(
    params: Params,
    prompt: jax.Array,
    config: TransformerConfig,
    *,
    max_new_tokens: int,
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
    max_len: Optional[int] = None,
) -> jax.Array:
    """Greedy/temperature sampling. prompt: [B, P] → [B, P+max_new_tokens]."""
    b, p = prompt.shape
    total = max_len or min(config.max_seq_len, p + max_new_tokens)
    cache = init_cache(config, b, total)
    w = config.uniform_window
    if w and cache["k"].shape[2] == w and p > w:
        # ring cache + long prompt: prefill in window-sized chunks so HBM
        # stays O(window) even for prompts far beyond it (the long-context
        # serving case SWA exists for); the tail chunk keeps its own
        # compiled shape
        logits = None
        for i in range(0, p, w):
            logits, cache = decode_step(params, cache, prompt[:, i:i + w],
                                        config)
    else:
        logits, cache = decode_step(params, cache, prompt, config)
    last = logits[:, -1]

    def sample(logits, key):
        if temperature <= 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return jax.random.categorical(key, logits / temperature).astype(jnp.int32)

    if rng is None:
        rng = jax.random.PRNGKey(0)

    def step(carry, key):
        cache, last_logits = carry
        tok = sample(last_logits, key)
        logits, cache = decode_step(params, cache, tok[:, None], config)
        return (cache, logits[:, -1]), tok

    keys = jax.random.split(rng, max_new_tokens)
    (_, _), toks = lax.scan(step, (cache, last), keys)
    return jnp.concatenate([prompt, toks.T], axis=1)
