// TSAN stress harness for the arena store (reference role: the C++ core's
// TSAN CI gate, SURVEY §5 "keep TSAN-clean C++ core as a CI gate").
//
// Hammers one arena from several threads: create/seal/get/release/delete
// race while an eviction thread applies pressure. Run under
// -fsanitize=thread via `make -C native tsan` — any data race in the
// store's mutex/refcount/free-list logic trips the sanitizer.

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

// Prototypes MUST match store.cc exactly (mismatched function types are
// UB that can miscompile under LTO/CFI — defeating a sanitizer gate).
struct Store;
extern "C" {
Store *rtpu_store_open(const char *name, uint64_t capacity);
void rtpu_store_close(Store *store);
uint64_t rtpu_create(Store *store, const uint8_t *id, uint64_t size);
int rtpu_seal(Store *store, const uint8_t *id);
uint64_t rtpu_get(Store *store, const uint8_t *id, uint64_t *size);
int rtpu_contains(Store *store, const uint8_t *id);
int rtpu_release(Store *store, const uint8_t *id);
int rtpu_delete(Store *store, const uint8_t *id);
uint64_t rtpu_evict(Store *store, uint64_t nbytes);
void rtpu_stats(Store *store, uint64_t *cap, uint64_t *used, uint64_t *num);
uint8_t *rtpu_base(Store *store);
void rtpu_store_destroy(const char *name);
}

static const int kThreads = 4;
static const int kIters = 800;
static const uint64_t kObjSize = 64 * 1024;

static void make_id(uint8_t *out, int thread, int i) {
  // 20-byte id field; zero-pad
  std::memset(out, 0, 20);
  std::snprintf(reinterpret_cast<char *>(out), 20, "t%02d-%06d", thread, i);
}

int main() {
  const char *name = "/rtpu-arena-tsan-stress";
  rtpu_store_destroy(name);
  Store *store = rtpu_store_open(name, 64ull << 20);  // small: forces churn
  if (!store) {
    std::fprintf(stderr, "open failed\n");
    return 1;
  }
  uint8_t *base = rtpu_base(store);
  std::atomic<bool> stop{false};
  std::atomic<uint64_t> created{0}, read_ok{0};

  std::vector<std::thread> workers;
  for (int t = 0; t < kThreads; ++t) {
    workers.emplace_back([&, t] {
      uint8_t id[20];
      for (int i = 0; i < kIters; ++i) {
        make_id(id, t, i);
        uint64_t off = rtpu_create(store, id, kObjSize);
        if (off != 0) {
          std::memset(base + off, t + 1, kObjSize);
          rtpu_seal(store, id);
          rtpu_release(store, id);  // drop the create ref: evictable
          created.fetch_add(1);
        }
        // read a neighbor thread's recent object
        make_id(id, (t + 1) % kThreads, i > 10 ? i - 10 : 0);
        uint64_t size = 0;
        uint64_t roff = rtpu_get(store, id, &size);
        if (roff != 0) {
          volatile uint8_t sink = base[roff];  // touch shared bytes
          (void)sink;
          rtpu_release(store, id);
          read_ok.fetch_add(1);
        }
        // churn: delete our own older object
        if (i > 20) {
          make_id(id, t, i - 20);
          rtpu_delete(store, id);
        }
      }
    });
  }
  std::thread evictor([&] {
    while (!stop.load()) {
      rtpu_evict(store, 4ull << 20);
      std::this_thread::yield();
    }
  });
  for (auto &w : workers) w.join();
  stop.store(true);
  evictor.join();

  uint64_t cap = 0, used = 0, num = 0;
  rtpu_stats(store, &cap, &used, &num);
  std::printf("tsan-stress ok: created=%llu read=%llu live=%llu used=%llu\n",
              (unsigned long long)created.load(),
              (unsigned long long)read_ok.load(),
              (unsigned long long)num, (unsigned long long)used);
  rtpu_store_close(store);
  rtpu_store_destroy(name);
  return 0;
}
