// rtpu native driver engine: GIL-free control-pipe transport + data-plane
// primitives.
//
// Role analog: the reference's C++ CoreWorker threads behind the Cython
// bridge (src/ray/core_worker/core_worker.h) — the entire reason
// _raylet.pyx exists is so the per-task control costs (framing, socket IO,
// refcount bookkeeping) are paid off the GIL. Here the driver attaches one
// engine per worker connection fd:
//
//   - sender thread: pops pre-pickled messages from a queue, coalesces
//     whatever accumulated while the previous write was in flight into ONE
//     multiprocessing-compatible frame (a batch frame when >1), and writes
//     it. Python's per-send cost drops to pickle + one ctypes enqueue.
//   - drain-side receiver: the Python reader thread's drain() call does
//     the length-prefix reads itself with the GIL released — one kernel
//     wake per burst, no intermediate thread hop — splitting batch
//     frames and applying refpin delta frames to a native per-connection
//     refcount table (only net 0<->1 transitions reach the interpreter).
//
// Wire formats (shared with the pure-Python fallback paths, which must
// keep understanding them when the .so is absent on one side):
//   frame     = mp framing: u32be len payload   (len==0xffffffff: u64be len)
//   payload   = pickle bytes
//             | "RTB1" u32be count ( u32be len pickle )*   [batch]
//             | "RTP1" ( id[16] i8 delta )*                [refpin deltas]
// Pickle payloads always start with 0x80 (protocol >= 2), so the ASCII
// magics cannot collide.
//
// Data plane: rtpu_copy_mt (persistent-pool multi-threaded memcpy for
// large put/get against the arena) and an LZ4-block-format codec for the
// spill/restore path (no lz4/zstd python modules in the image; the codec
// is self-contained and tested by roundtrip against random + structured
// data).

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <errno.h>
#include <poll.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

namespace {

constexpr uint32_t kIdBytes16 = 16;  // control-plane ObjectID width (ids.py _ID_LEN)
const uint8_t kBatchMagic[4] = {'R', 'T', 'B', '1'};
const uint8_t kRefpinMagic[4] = {'R', 'T', 'P', '1'};

// -- low-level IO -----------------------------------------------------------

bool write_all(int fd, const uint8_t* buf, uint64_t n) {
  while (n > 0) {
    ssize_t w = ::write(fd, buf, n);
    if (w < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    buf += w;
    n -= static_cast<uint64_t>(w);
  }
  return true;
}

void put_u32be(std::string& out, uint32_t v) {
  out.push_back(static_cast<char>((v >> 24) & 0xff));
  out.push_back(static_cast<char>((v >> 16) & 0xff));
  out.push_back(static_cast<char>((v >> 8) & 0xff));
  out.push_back(static_cast<char>(v & 0xff));
}

void put_u32le(std::string& out, uint32_t v) {
  out.push_back(static_cast<char>(v & 0xff));
  out.push_back(static_cast<char>((v >> 8) & 0xff));
  out.push_back(static_cast<char>((v >> 16) & 0xff));
  out.push_back(static_cast<char>((v >> 24) & 0xff));
}

uint32_t get_u32be(const uint8_t* p) {
  return (static_cast<uint32_t>(p[0]) << 24) |
         (static_cast<uint32_t>(p[1]) << 16) |
         (static_cast<uint32_t>(p[2]) << 8) | static_cast<uint32_t>(p[3]);
}

// -- pipe engine ------------------------------------------------------------

struct NativePipe {
  int fd = -1;
  uint64_t coalesce_us = 0;

  // send side
  std::mutex smu;
  std::condition_variable scv;
  std::deque<std::string> sendq;
  std::string partial;  // pre-framed bytes an inline write couldn't finish
  bool sender_busy = false;  // sender thread mid-write with smu RELEASED
  bool closing = false;
  std::thread sender;

  // recv side. Reads happen ON the drain call itself (GIL released via
  // ctypes): the kernel wakes the draining thread directly — one thread
  // hop, exactly like a plain Python reader — while framing, batch
  // splitting and refpin bookkeeping stay native. rq buffers records
  // that did not fit the caller's buffer; it is touched only by the
  // single drain thread, so it needs no lock.
  std::string rq;
  size_t rq_off = 0;  // consumed prefix (compacted when fully drained)
  std::string rbuf;   // raw socket bytes not yet parsed into frames
  size_t rbuf_off = 0;
  int rcvtimeo_ms = -1;  // last SO_RCVTIMEO applied (syscall cache)
  bool eof = false;

  // per-connection borrow refcounts (the worker's ws.pinned twin),
  // maintained natively so refpin batches never touch the interpreter.
  // rmu guards pins only (drain thread vs the death-path drain_pins).
  std::mutex rmu;
  std::map<std::string, int64_t> pins;

  // counters (read by rtpu_pipe_stats)
  std::atomic<uint64_t> c_sent_frames{0}, c_sent_msgs{0}, c_sent_bytes{0};
  std::atomic<uint64_t> c_recv_frames{0}, c_recv_msgs{0}, c_recv_bytes{0};
  std::atomic<uint64_t> c_refpin_deltas{0}, c_refpin_transitions{0};
};

void append_record(NativePipe* p, uint8_t type, const uint8_t* data,
                   uint64_t len) {
  // drain-thread only (rq is single-consumer overflow)
  p->rq.push_back(static_cast<char>(type));
  put_u32le(p->rq, static_cast<uint32_t>(len));
  p->rq.append(reinterpret_cast<const char*>(data), len);
}

// Frame header into hdr (mp wire format); returns header length.
int frame_header(uint64_t payload_len, uint8_t* hdr) {
  if (payload_len > 0x7fffffffull) {
    hdr[0] = hdr[1] = hdr[2] = hdr[3] = 0xff;  // struct.pack("!i", -1)
    for (int i = 0; i < 8; i++)
      hdr[4 + i] = static_cast<uint8_t>((payload_len >> (8 * (7 - i))) &
                                        0xff);
    return 12;
  }
  hdr[0] = static_cast<uint8_t>((payload_len >> 24) & 0xff);
  hdr[1] = static_cast<uint8_t>((payload_len >> 16) & 0xff);
  hdr[2] = static_cast<uint8_t>((payload_len >> 8) & 0xff);
  hdr[3] = static_cast<uint8_t>(payload_len & 0xff);
  return 4;
}

// One frame for a batch of messages (single = raw payload, >1 = RTB1).
std::string build_frame(const std::deque<std::string>& batch) {
  std::string frame;
  uint64_t payload_len;
  if (batch.size() == 1) {
    payload_len = batch[0].size();
  } else {
    payload_len = 8;  // magic + count
    for (const auto& m : batch) payload_len += 4 + m.size();
  }
  frame.reserve(payload_len + 12);
  uint8_t hdr[12];
  int hlen = frame_header(payload_len, hdr);
  frame.append(reinterpret_cast<const char*>(hdr), hlen);
  if (batch.size() == 1) {
    frame += batch[0];
  } else {
    frame.append(reinterpret_cast<const char*>(kBatchMagic), 4);
    put_u32be(frame, static_cast<uint32_t>(batch.size()));
    for (const auto& m : batch) {
      put_u32be(frame, static_cast<uint32_t>(m.size()));
      frame += m;
    }
  }
  return frame;
}

void sender_loop(NativePipe* p) {
  // The SLOW path: engaged only when an inline nonblocking send could not
  // finish (socket buffer full) or messages queued behind one. That is
  // exactly when coalescing pays — everything queued while this thread's
  // previous write was in flight ships as one batch frame.
  std::unique_lock<std::mutex> lk(p->smu);
  for (;;) {
    while (p->sendq.empty() && p->partial.empty() && !p->closing)
      p->scv.wait(lk);
    if (p->sendq.empty() && p->partial.empty()) return;  // closing, done
    if (p->coalesce_us > 0 && p->partial.empty() && p->sendq.size() == 1 &&
        !p->closing) {
      // optional Nagle window (default 0: natural coalescing only)
      p->scv.wait_for(lk, std::chrono::microseconds(p->coalesce_us));
    }
    std::string head;
    head.swap(p->partial);  // pre-framed remainder goes FIRST
    std::deque<std::string> batch;
    batch.swap(p->sendq);
    // the flag keeps the inline fast path OFF the socket while this
    // thread writes with the lock released — without it a send arriving
    // mid-write_all would interleave its frame into ours
    p->sender_busy = true;
    lk.unlock();

    bool ok = true;
    if (!head.empty())
      ok = write_all(p->fd, reinterpret_cast<const uint8_t*>(head.data()),
                     head.size());
    if (ok && !batch.empty()) {
      std::string frame = build_frame(batch);
      ok = write_all(p->fd,
                     reinterpret_cast<const uint8_t*>(frame.data()),
                     frame.size());
      p->c_sent_frames.fetch_add(1, std::memory_order_relaxed);
      p->c_sent_msgs.fetch_add(batch.size(), std::memory_order_relaxed);
      p->c_sent_bytes.fetch_add(frame.size(), std::memory_order_relaxed);
    }
    lk.lock();
    p->sender_busy = false;
    if (!ok) {  // peer gone; the receiver's EOF drives Python-side death
      p->closing = true;
      p->sendq.clear();
      p->partial.clear();
      return;
    }
  }
}

// Apply a packed refpin frame to the native borrow table; returns the
// packed NET transitions (id[16] + i8)* to surface to Python, usually
// empty or tiny.
std::string apply_refpins(NativePipe* p, const uint8_t* data,
                          uint64_t len) {
  std::string trans;
  std::lock_guard<std::mutex> lk(p->rmu);
  for (uint64_t off = 0; off + kIdBytes16 + 1 <= len;
       off += kIdBytes16 + 1) {
    std::string id(reinterpret_cast<const char*>(data + off), kIdBytes16);
    int8_t d = static_cast<int8_t>(data[off + kIdBytes16]);
    p->c_refpin_deltas.fetch_add(1, std::memory_order_relaxed);
    int64_t before = 0;
    auto it = p->pins.find(id);
    if (it != p->pins.end()) before = it->second;
    int64_t after = before + d;
    if (after > 0) {
      p->pins[id] = after;
    } else if (it != p->pins.end()) {
      p->pins.erase(it);
    }
    if (before == 0 && after > 0) {
      trans += id;
      trans.push_back(1);
    } else if (before > 0 && after <= 0) {
      trans += id;
      trans.push_back(static_cast<char>(-1));
    }
  }
  if (!trans.empty())
    p->c_refpin_transitions.fetch_add(trans.size() / (kIdBytes16 + 1),
                                      std::memory_order_relaxed);
  return trans;
}

// Record sink for the drain call: fills the caller buffer while records
// fit AND the overflow queue is empty (order preservation); everything
// else lands in the overflow queue for the next call.
struct DrainSink {
  NativePipe* p;
  uint8_t* out;
  uint64_t cap;
  uint64_t copied = 0;
};

void sink_record(DrainSink& s, uint8_t type, const uint8_t* data,
                 uint64_t len) {
  uint64_t rec = 5ull + len;
  if (s.p->rq.size() == s.p->rq_off && s.copied + rec <= s.cap) {
    s.out[s.copied] = static_cast<char>(type);
    uint32_t l32 = static_cast<uint32_t>(len);
    memcpy(s.out + s.copied + 1, &l32, 4);
    memcpy(s.out + s.copied + 5, data, len);
    s.copied += rec;
  } else {
    append_record(s.p, type, data, len);
  }
}

// Parse one complete frame payload into records.
void ingest_frame(DrainSink& s, const uint8_t* payload, uint64_t n) {
  NativePipe* p = s.p;
  p->c_recv_frames.fetch_add(1, std::memory_order_relaxed);
  p->c_recv_bytes.fetch_add(n + 4, std::memory_order_relaxed);
  if (n > 4 && memcmp(payload, kRefpinMagic, 4) == 0) {
    std::string trans = apply_refpins(p, payload + 4, n - 4);
    if (!trans.empty())
      sink_record(s, 1, reinterpret_cast<const uint8_t*>(trans.data()),
                  trans.size());
    return;
  }
  if (n >= 8 && memcmp(payload, kBatchMagic, 4) == 0) {
    uint32_t count = get_u32be(payload + 4);
    uint64_t off = 8;
    for (uint32_t i = 0; i < count && off + 4 <= n; i++) {
      uint32_t ln = get_u32be(payload + off);
      off += 4;
      if (off + ln > n) break;
      sink_record(s, 0, payload + off, ln);
      p->c_recv_msgs.fetch_add(1, std::memory_order_relaxed);
      off += ln;
    }
    return;
  }
  sink_record(s, 0, payload, n);
  p->c_recv_msgs.fetch_add(1, std::memory_order_relaxed);
}

// Parse every COMPLETE frame sitting in rbuf into the sink; partial
// frames stay buffered for the next recv.
void parse_rbuf(DrainSink& s) {
  NativePipe* p = s.p;
  for (;;) {
    const uint8_t* base =
        reinterpret_cast<const uint8_t*>(p->rbuf.data()) + p->rbuf_off;
    uint64_t avail = p->rbuf.size() - p->rbuf_off;
    if (avail < 4) break;
    uint64_t n = get_u32be(base);
    uint64_t hlen = 4;
    if (n == 0xffffffffu) {  // mp extended 64-bit length
      if (avail < 12) break;
      n = 0;
      for (int i = 0; i < 8; i++) n = (n << 8) | base[4 + i];
      hlen = 12;
    }
    if (avail < hlen + n) break;
    ingest_frame(s, base + hlen, n);
    p->rbuf_off += hlen + n;
  }
  if (p->rbuf_off == p->rbuf.size()) {
    p->rbuf.clear();
    p->rbuf_off = 0;
  } else if (p->rbuf_off > (1u << 20)) {
    p->rbuf.erase(0, p->rbuf_off);
    p->rbuf_off = 0;
  }
}

// -- multi-threaded memcpy pool ---------------------------------------------

struct CopyShard {
  uint8_t* dst;
  const uint8_t* src;
  uint64_t n;
  std::atomic<int>* done;
};

class CopyPool {
 public:
  static CopyPool& instance() {
    // intentionally leaked: a static-duration pool would run its
    // destructor at process exit while detached workers still wait on
    // the condition variable — glibc deadlocks in __run_exit_handlers
    static CopyPool* pool = new CopyPool();
    return *pool;
  }

  void submit(const CopyShard& s) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      q_.push_back(s);
    }
    cv_.notify_one();
  }

  int workers() const { return nworkers_; }

 private:
  CopyPool() {
    unsigned hc = std::thread::hardware_concurrency();
    nworkers_ = hc > 1 ? static_cast<int>(hc > 8 ? 8 : hc) - 1 : 1;
    for (int i = 0; i < nworkers_; i++)
      std::thread([this] { worker(); }).detach();
  }

  void worker() {
    for (;;) {
      CopyShard s;
      {
        std::unique_lock<std::mutex> lk(mu_);
        while (q_.empty()) cv_.wait(lk);
        s = q_.front();
        q_.pop_front();
      }
      memcpy(s.dst, s.src, s.n);
      s.done->fetch_add(1, std::memory_order_release);
    }
  }

  std::mutex mu_;
  std::condition_variable cv_;
  std::deque<CopyShard> q_;
  int nworkers_ = 1;
};

// -- LZ4 block codec --------------------------------------------------------
//
// Standard LZ4 block format (token / literals / le16 offset / matchlen),
// self-contained. Correctness contract: decompress(compress(x)) == x for
// every input; the compressor respects the end-of-block rules (last 5
// bytes literal, no match starting within the last 12 bytes).

constexpr int kHashLog = 13;
constexpr uint32_t kHashSize = 1u << kHashLog;

inline uint32_t read32(const uint8_t* p) {
  uint32_t v;
  memcpy(&v, p, 4);
  return v;
}

inline uint32_t lz_hash(uint32_t v) {
  return (v * 2654435761u) >> (32 - kHashLog);
}

}  // namespace

extern "C" {

// ---------------------------------------------------------------------------
// pipe engine C API
// ---------------------------------------------------------------------------

NativePipe* rtpu_pipe_new(int fd, uint64_t coalesce_us) {
  NativePipe* p = new NativePipe();
  p->fd = fd;
  p->coalesce_us = coalesce_us;
  p->sender = std::thread(sender_loop, p);
  return p;
}

// Send one pre-pickled message. 0 ok, -1 closed.
//
// Fast path (queues empty): frame and write INLINE with MSG_DONTWAIT — no
// thread handoff at all, same single syscall the Python sender paid. On a
// full socket buffer (or with messages already queued) the remainder goes
// to the sender thread, which batches everything that accumulates.
int rtpu_pipe_send(NativePipe* p, const uint8_t* buf, uint64_t len) {
  bool wake = false;
  {
    std::lock_guard<std::mutex> lk(p->smu);
    if (p->closing) return -1;
    if (p->sendq.empty() && p->partial.empty() && !p->sender_busy) {
      uint8_t hdr[12];
      int hlen = frame_header(len, hdr);
      struct iovec iov[2];
      iov[0].iov_base = hdr;
      iov[0].iov_len = static_cast<size_t>(hlen);
      iov[1].iov_base = const_cast<uint8_t*>(buf);
      iov[1].iov_len = len;
      struct msghdr mh;
      memset(&mh, 0, sizeof(mh));
      mh.msg_iov = iov;
      mh.msg_iovlen = 2;
      ssize_t w = ::sendmsg(p->fd, &mh, MSG_DONTWAIT | MSG_NOSIGNAL);
      uint64_t total = static_cast<uint64_t>(hlen) + len;
      if (w == static_cast<ssize_t>(total)) {
        p->c_sent_frames.fetch_add(1, std::memory_order_relaxed);
        p->c_sent_msgs.fetch_add(1, std::memory_order_relaxed);
        p->c_sent_bytes.fetch_add(total, std::memory_order_relaxed);
        return 0;
      }
      if (w < 0 && errno != EAGAIN && errno != EWOULDBLOCK &&
          errno != EINTR) {
        p->closing = true;
        return -1;
      }
      // partial (or EAGAIN): stash the pre-framed remainder for the
      // sender thread; frame order is preserved (partial goes first)
      uint64_t done = w > 0 ? static_cast<uint64_t>(w) : 0;
      p->partial.reserve(total - done);
      if (done < static_cast<uint64_t>(hlen)) {
        p->partial.append(reinterpret_cast<const char*>(hdr) + done,
                          hlen - done);
        done = 0;
      } else {
        done -= hlen;
      }
      p->partial.append(reinterpret_cast<const char*>(buf) + done,
                        len - done);
      p->c_sent_frames.fetch_add(1, std::memory_order_relaxed);
      p->c_sent_msgs.fetch_add(1, std::memory_order_relaxed);
      p->c_sent_bytes.fetch_add(total, std::memory_order_relaxed);
      wake = true;
    } else {
      p->sendq.emplace_back(reinterpret_cast<const char*>(buf), len);
      wake = true;
    }
  }
  if (wake) p->scv.notify_one();
  return 0;
}

// Drain records into out (packed [u8 type][u32le len][payload]*).
//
// Called by ONE Python thread per connection (its reader thread), with
// the GIL released via ctypes. Syscall-frugal by design — syscalls on
// the sandboxed boxes this runs on cost tens of µs: steady state is ONE
// recv(2) per wake (SO_RCVTIMEO bounds the block; no poll), and a burst
// of frames arrives in one recv and parses out of the user-space buffer.
// Returns bytes written; 0 on timeout; -1 on EOF with nothing queued;
// -needed when the first record alone exceeds cap.
int64_t rtpu_pipe_drain(NativePipe* p, uint8_t* out, uint64_t cap,
                        uint64_t timeout_ms) {
  // 1. leftover records from a previous overflow
  if (p->rq.size() > p->rq_off) {
    const uint8_t* base = reinterpret_cast<const uint8_t*>(p->rq.data());
    uint64_t off = p->rq_off;
    uint64_t copied = 0;
    while (off < p->rq.size()) {
      uint32_t len;
      memcpy(&len, base + off + 1, 4);
      uint64_t rec = 5ull + len;
      if (copied + rec > cap) {
        if (copied == 0) return -static_cast<int64_t>(rec);
        break;
      }
      memcpy(out + copied, base + off, rec);
      copied += rec;
      off += rec;
    }
    p->rq_off = off;
    if (p->rq_off == p->rq.size()) {
      p->rq.clear();
      p->rq_off = 0;
    }
    return static_cast<int64_t>(copied);
  }

  DrainSink sink{p, out, cap};
  // 2. frames already buffered from a previous recv
  parse_rbuf(sink);
  for (;;) {
    if (sink.copied > 0) return static_cast<int64_t>(sink.copied);
    if (p->rq.size() > p->rq_off) {
      // a record bigger than cap went straight to overflow
      uint32_t len;
      memcpy(&len, p->rq.data() + p->rq_off + 1, 4);
      return -static_cast<int64_t>(5ull + len);
    }
    if (p->eof) return -1;

    // 3. one bounded blocking recv — THE syscall of the steady state
    if (p->rcvtimeo_ms != static_cast<int>(timeout_ms)) {
      struct timeval tv;
      tv.tv_sec = timeout_ms / 1000;
      tv.tv_usec = (timeout_ms % 1000) * 1000;
      setsockopt(p->fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
      p->rcvtimeo_ms = static_cast<int>(timeout_ms);
    }
    char tmp[256 << 10];
    ssize_t r = ::recv(p->fd, tmp, sizeof(tmp), 0);
    if (r == 0) {
      p->eof = true;
      return -1;
    }
    if (r < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR)
        return 0;  // timeout tick: caller re-checks shutdown state
      p->eof = true;
      return -1;
    }
    p->rbuf.append(tmp, static_cast<size_t>(r));
    parse_rbuf(sink);
    // loop: a partial frame keeps reading; completed records return
  }
}

// Serialize-and-clear the connection's borrow table (worker-death drain):
// packed (id[16] + i64le count)*. Returns bytes or -needed.
int64_t rtpu_pipe_drain_pins(NativePipe* p, uint8_t* out, uint64_t cap) {
  std::lock_guard<std::mutex> lk(p->rmu);
  uint64_t need = p->pins.size() * (kIdBytes16 + 8ull);
  if (need > cap) return -static_cast<int64_t>(need);
  uint64_t off = 0;
  for (const auto& kv : p->pins) {
    memcpy(out + off, kv.first.data(), kIdBytes16);
    int64_t c = kv.second;
    memcpy(out + off + kIdBytes16, &c, 8);
    off += kIdBytes16 + 8;
  }
  p->pins.clear();
  return static_cast<int64_t>(off);
}

void rtpu_pipe_stats(NativePipe* p, uint64_t* out8) {
  out8[0] = p->c_sent_frames.load(std::memory_order_relaxed);
  out8[1] = p->c_sent_msgs.load(std::memory_order_relaxed);
  out8[2] = p->c_sent_bytes.load(std::memory_order_relaxed);
  out8[3] = p->c_recv_frames.load(std::memory_order_relaxed);
  out8[4] = p->c_recv_msgs.load(std::memory_order_relaxed);
  out8[5] = p->c_recv_bytes.load(std::memory_order_relaxed);
  out8[6] = p->c_refpin_deltas.load(std::memory_order_relaxed);
  out8[7] = p->c_refpin_transitions.load(std::memory_order_relaxed);
}

// Stop accepting sends and unblock the sender thread + any blocked
// drain (shutdown(2) makes poll/read return immediately). Does NOT close
// the fd (Python's Connection object owns it) and does not join — safe
// to call from the drain thread itself.
void rtpu_pipe_shutdown(NativePipe* p) {
  {
    std::lock_guard<std::mutex> lk(p->smu);
    p->closing = true;
  }
  p->scv.notify_all();
  ::shutdown(p->fd, SHUT_RDWR);
}

// Full teardown: shutdown + join + delete. Never call from the engine's
// own threads (the Python drain thread is fine — it is a Python thread;
// the wrapper's in-flight guard keeps it out of the struct first).
void rtpu_pipe_close(NativePipe* p) {
  rtpu_pipe_shutdown(p);
  if (p->sender.joinable()) p->sender.join();
  delete p;
}

// ---------------------------------------------------------------------------
// multi-threaded memcpy
// ---------------------------------------------------------------------------

// Copy n bytes dst<-src with up to `threads` workers (the calling thread
// copies its own shard; ctypes releases the GIL around the call, so pool
// workers run truly parallel to it). Small copies fall through to plain
// memcpy.
void rtpu_copy_mt(uint8_t* dst, const uint8_t* src, uint64_t n,
                  int threads) {
  CopyPool& pool = CopyPool::instance();
  int k = threads;
  int avail = pool.workers() + 1;
  if (k <= 0 || k > avail) k = avail;
  if (k <= 1 || n < (1u << 20)) {
    memcpy(dst, src, n);
    return;
  }
  std::atomic<int> done{0};
  uint64_t shard = (n / k + 63) & ~63ull;  // cacheline-aligned shards
  int submitted = 0;
  uint64_t off = shard;  // shard 0 is the caller's
  for (int i = 1; i < k && off < n; i++) {
    uint64_t len = (i == k - 1) ? n - off : (off + shard <= n ? shard
                                                              : n - off);
    pool.submit({dst + off, src + off, len, &done});
    submitted++;
    off += len;
  }
  memcpy(dst, src, shard < n ? shard : n);
  while (done.load(std::memory_order_acquire) < submitted)
    std::this_thread::yield();
}

// ---------------------------------------------------------------------------
// LZ4 block codec
// ---------------------------------------------------------------------------

uint64_t rtpu_lz4_bound(uint64_t n) { return n + n / 255 + 16; }

// Compress src[0..n) into dst (capacity cap). Returns compressed size, or
// -1 when dst is too small (callers then store the block raw).
int64_t rtpu_lz4_compress(const uint8_t* src, uint64_t n, uint8_t* dst,
                          uint64_t cap) {
  uint64_t op = 0;

  auto emit = [&](uint64_t lit_start, uint64_t lit_len, uint32_t offset,
                  uint64_t match_len) -> bool {
    // token
    uint64_t need = 1 + lit_len + lit_len / 255 + 1 + (offset ? 2 : 0) +
                    (match_len ? match_len / 255 + 1 : 0) + 8;
    if (op + need > cap) return false;
    uint8_t token = 0;
    uint64_t ml = match_len ? match_len - 4 : 0;
    token = static_cast<uint8_t>(
        ((lit_len >= 15 ? 15 : lit_len) << 4) |
        (offset ? (ml >= 15 ? 15 : ml) : 0));
    dst[op++] = token;
    if (lit_len >= 15) {
      uint64_t rest = lit_len - 15;
      while (rest >= 255) {
        dst[op++] = 255;
        rest -= 255;
      }
      dst[op++] = static_cast<uint8_t>(rest);
    }
    // guard: memcpy's args are declared nonnull, and a zero-byte input
    // arrives as src == nullptr (empty buffer) — UB even with len 0
    if (lit_len) memcpy(dst + op, src + lit_start, lit_len);
    op += lit_len;
    if (offset) {
      dst[op++] = static_cast<uint8_t>(offset & 0xff);
      dst[op++] = static_cast<uint8_t>((offset >> 8) & 0xff);
      if (ml >= 15) {
        uint64_t rest = ml - 15;
        while (rest >= 255) {
          dst[op++] = 255;
          rest -= 255;
        }
        dst[op++] = static_cast<uint8_t>(rest);
      }
    }
    return true;
  };

  if (n < 13) {  // too small for any match per the format's end rules
    if (!emit(0, n, 0, 0)) return -1;
    return static_cast<int64_t>(op);
  }

  std::vector<uint32_t> table(kHashSize, 0);  // pos+1; 0 = empty
  uint64_t mflimit = n - 12;  // no match may START past here
  uint64_t pos = 0, anchor = 0;
  while (pos <= mflimit) {
    uint32_t seq = read32(src + pos);
    uint32_t h = lz_hash(seq);
    uint64_t ref = table[h];
    table[h] = static_cast<uint32_t>(pos + 1);
    if (ref != 0) {
      uint64_t r = ref - 1;
      if (pos - r <= 65535 && read32(src + r) == seq) {
        // extend the match, but leave the last 5 bytes as literals
        uint64_t limit = n - 5;
        uint64_t mlen = 4;
        while (pos + mlen < limit && src[r + mlen] == src[pos + mlen])
          mlen++;
        if (!emit(anchor, pos - anchor,
                  static_cast<uint32_t>(pos - r), mlen))
          return -1;
        pos += mlen;
        anchor = pos;
        continue;
      }
    }
    pos++;
  }
  if (!emit(anchor, n - anchor, 0, 0)) return -1;
  return static_cast<int64_t>(op);
}

// Decompress src[0..n) into dst (exact capacity dcap). Returns bytes
// produced, or -1 on malformed input / overflow.
int64_t rtpu_lz4_decompress(const uint8_t* src, uint64_t n, uint8_t* dst,
                            uint64_t dcap) {
  uint64_t ip = 0, op = 0;
  while (ip < n) {
    uint8_t token = src[ip++];
    uint64_t lit = token >> 4;
    if (lit == 15) {
      uint8_t b;
      do {
        if (ip >= n) return -1;
        b = src[ip++];
        lit += b;
      } while (b == 255);
    }
    if (ip + lit > n || op + lit > dcap) return -1;
    memcpy(dst + op, src + ip, lit);
    ip += lit;
    op += lit;
    if (ip >= n) break;  // final sequence: literals only
    if (ip + 2 > n) return -1;
    uint32_t offset = src[ip] | (static_cast<uint32_t>(src[ip + 1]) << 8);
    ip += 2;
    if (offset == 0 || offset > op) return -1;
    uint64_t mlen = (token & 15);
    if (mlen == 15) {
      uint8_t b;
      do {
        if (ip >= n) return -1;
        b = src[ip++];
        mlen += b;
      } while (b == 255);
    }
    mlen += 4;
    if (op + mlen > dcap) return -1;
    // byte-wise copy: overlapping matches (offset < mlen) are the RLE case
    const uint8_t* m = dst + op - offset;
    for (uint64_t i = 0; i < mlen; i++) dst[op + i] = m[i];
    op += mlen;
  }
  return static_cast<int64_t>(op);
}

}  // extern "C"
