// rtpu native object store: one shared-memory arena per session.
//
// Role analog: the reference's plasma store (src/ray/object_manager/plasma/
// store.h + object_lifecycle_manager.h + dlmalloc arena) re-designed for the
// single-daemonless model this framework uses: instead of a store server
// process speaking a unix-socket protocol, the arena itself IS the shared
// state — a POSIX shm segment containing the allocator metadata, the object
// table, and the payload heap, guarded by a process-shared robust mutex.
// Writers allocate+seal; readers look up sealed entries and pin them with a
// refcount; eviction walks sealed refcount-0 objects in LRU order (the
// reference's EvictionPolicy).
//
// Exposed as a flat C API consumed from Python via ctypes (no pybind11 in
// the image). All offsets are relative to the arena base so every process
// can mmap at a different address.

#include <cstdint>
#include <cstring>
#include <cerrno>
#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x52545055'53544f52ULL;  // "RTPUSTOR"
constexpr uint32_t kIdBytes = 20;                    // ObjectID binary size
constexpr uint32_t kMaxObjects = 65536;              // table capacity (pow2)
constexpr uint64_t kAlign = 64;                      // cacheline alignment

enum EntryState : uint32_t {
  kFree = 0,       // slot unused
  kCreated = 1,    // allocated, writer still filling
  kSealed = 2,     // immutable, readable
  kTombstone = 3,  // deleted; slot reusable but keeps probe chains alive
  kDeleting = 4,   // delete requested with live readers; freed on last release
};

struct Entry {
  uint8_t id[kIdBytes];
  uint32_t state;
  uint64_t offset;      // payload offset from arena base
  uint64_t size;        // payload size (what readers see)
  uint64_t alloc_size;  // bytes actually taken from the heap (>= size)
  int64_t refcount;
  uint64_t lru_tick;    // last pin/unpin tick for eviction ordering
};

// Free-list node stored inside the free block itself.
struct FreeBlock {
  uint64_t size;
  uint64_t next;       // offset of next free block, 0 == end
};

struct Header {
  uint64_t magic;
  uint64_t arena_size;     // total mapping size
  uint64_t heap_start;     // first payload byte
  uint64_t free_head;      // offset of first free block (0 == none)
  uint64_t used_bytes;     // payload bytes currently allocated
  uint64_t lru_clock;      // monotonic tick
  uint64_t num_objects;
  pthread_mutex_t lock;    // process-shared robust mutex
  Entry table[kMaxObjects];
};

struct Store {
  Header* hdr;
  uint8_t* base;
  uint64_t map_size;
  int fd;
};

uint64_t align_up(uint64_t v) { return (v + kAlign - 1) & ~(kAlign - 1); }

uint32_t id_hash(const uint8_t* id) {
  // FNV-1a over the 20-byte id.
  uint64_t h = 1469598103934665603ULL;
  for (uint32_t i = 0; i < kIdBytes; i++) {
    h ^= id[i];
    h *= 1099511628211ULL;
  }
  return static_cast<uint32_t>(h & (kMaxObjects - 1));
}

class Locker {
 public:
  explicit Locker(Header* hdr) : hdr_(hdr) {
    int rc = pthread_mutex_lock(&hdr_->lock);
    if (rc == EOWNERDEAD) {
      // A process died holding the lock; state is best-effort consistent
      // (allocator ops are short); mark recovered.
      pthread_mutex_consistent(&hdr_->lock);
    }
  }
  ~Locker() { pthread_mutex_unlock(&hdr_->lock); }

 private:
  Header* hdr_;
};

// Find the table slot for id (existing entry or insertion point).
Entry* find_slot(Header* hdr, const uint8_t* id, bool for_insert) {
  uint32_t idx = id_hash(id);
  Entry* first_tomb = nullptr;
  for (uint32_t probe = 0; probe < kMaxObjects; probe++) {
    Entry* e = &hdr->table[(idx + probe) & (kMaxObjects - 1)];
    if (e->state == kFree) {
      if (for_insert) return first_tomb ? first_tomb : e;
      return nullptr;
    }
    if (e->state == kTombstone) {
      if (for_insert && !first_tomb) first_tomb = e;
      continue;
    }
    if (memcmp(e->id, id, kIdBytes) == 0) return e;
  }
  return for_insert ? first_tomb : nullptr;
}

// First-fit allocation from the free list. Returns 0 on failure; on
// success *consumed is the exact byte count taken from the heap (the whole
// block when the remainder is too small to split — callers must free with
// this value or the remainder leaks).
uint64_t heap_alloc(Header* hdr, uint8_t* base, uint64_t size,
                    uint64_t* consumed) {
  size = align_up(size);
  uint64_t prev_off = 0;
  uint64_t cur = hdr->free_head;
  while (cur) {
    FreeBlock* blk = reinterpret_cast<FreeBlock*>(base + cur);
    if (blk->size >= size) {
      uint64_t remaining = blk->size - size;
      uint64_t next = blk->next;
      uint64_t taken = size;
      if (remaining >= sizeof(FreeBlock) + kAlign) {
        uint64_t tail_off = cur + size;
        FreeBlock* tail = reinterpret_cast<FreeBlock*>(base + tail_off);
        tail->size = remaining;
        tail->next = next;
        next = tail_off;
      } else {
        taken = blk->size;  // absorb the unsplittable remainder
      }
      if (prev_off) {
        reinterpret_cast<FreeBlock*>(base + prev_off)->next = next;
      } else {
        hdr->free_head = next;
      }
      hdr->used_bytes += taken;
      *consumed = taken;
      return cur;
    }
    prev_off = cur;
    cur = blk->next;
  }
  return 0;
}

// Return a block to the free list, coalescing with adjacent free blocks.
// `size` must be the alloc_size heap_alloc reported for this block.
void heap_free(Header* hdr, uint8_t* base, uint64_t off, uint64_t size) {
  hdr->used_bytes -= size;
  // insert sorted by offset, then coalesce neighbors
  uint64_t prev = 0;
  uint64_t cur = hdr->free_head;
  while (cur && cur < off) {
    prev = cur;
    cur = reinterpret_cast<FreeBlock*>(base + cur)->next;
  }
  FreeBlock* blk = reinterpret_cast<FreeBlock*>(base + off);
  blk->size = size;
  blk->next = cur;
  if (prev) {
    FreeBlock* pb = reinterpret_cast<FreeBlock*>(base + prev);
    pb->next = off;
    if (prev + pb->size == off) {  // coalesce prev+this
      pb->size += blk->size;
      pb->next = blk->next;
      off = prev;
      blk = pb;
    }
  } else {
    hdr->free_head = off;
  }
  if (blk->next && off + blk->size == blk->next) {  // coalesce this+next
    FreeBlock* nb = reinterpret_cast<FreeBlock*>(base + blk->next);
    blk->size += nb->size;
    blk->next = nb->next;
  }
}

// Evict sealed refcount-0 objects in LRU order until at least `needed`
// bytes are free-able. Returns freed bytes.
uint64_t evict_lru(Header* hdr, uint8_t* base, uint64_t needed) {
  uint64_t freed = 0;
  while (freed < needed) {
    Entry* victim = nullptr;
    for (uint32_t i = 0; i < kMaxObjects; i++) {
      Entry* e = &hdr->table[i];
      if (e->state == kSealed && e->refcount == 0) {
        if (!victim || e->lru_tick < victim->lru_tick) victim = e;
      }
    }
    if (!victim) break;
    heap_free(hdr, base, victim->offset, victim->alloc_size);
    freed += victim->alloc_size;
    victim->state = kTombstone;
    hdr->num_objects--;
  }
  return freed;
}

}  // namespace

extern "C" {

// Create (or attach to) the arena for `name`. capacity used only on create.
Store* rtpu_store_open(const char* name, uint64_t capacity) {
  int fd = shm_open(name, O_RDWR, 0600);
  bool creator = false;
  if (fd < 0) {
    fd = shm_open(name, O_CREAT | O_RDWR | O_EXCL, 0600);
    if (fd < 0) {
      // lost a race: retry attach
      fd = shm_open(name, O_RDWR, 0600);
      if (fd < 0) return nullptr;
    } else {
      creator = true;
    }
  }
  uint64_t map_size;
  if (creator) {
    map_size = align_up(sizeof(Header)) + capacity;
    if (ftruncate(fd, static_cast<off_t>(map_size)) != 0) {
      close(fd);
      shm_unlink(name);
      return nullptr;
    }
  } else {
    struct stat st {};
    // creator may still be mid-ftruncate; spin briefly
    bool ok = false;
    for (int i = 0; i < 1000; i++) {
      if (fstat(fd, &st) == 0 && st.st_size > 0) {
        ok = true;
        break;
      }
      usleep(1000);
    }
    if (!ok) {
      close(fd);
      return nullptr;
    }
    map_size = static_cast<uint64_t>(st.st_size);
  }
  void* mem = mmap(nullptr, map_size, PROT_READ | PROT_WRITE, MAP_SHARED,
                   fd, 0);
  if (mem == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  Header* hdr = static_cast<Header*>(mem);
  uint8_t* base = static_cast<uint8_t*>(mem);
  if (creator) {
    memset(hdr, 0, sizeof(Header));
    hdr->arena_size = map_size;
    hdr->heap_start = align_up(sizeof(Header));
    FreeBlock* blk = reinterpret_cast<FreeBlock*>(base + hdr->heap_start);
    blk->size = map_size - hdr->heap_start;
    blk->next = 0;
    hdr->free_head = hdr->heap_start;
    pthread_mutexattr_t attr;
    pthread_mutexattr_init(&attr);
    pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
    pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
    pthread_mutex_init(&hdr->lock, &attr);
    pthread_mutexattr_destroy(&attr);
    __sync_synchronize();
    hdr->magic = kMagic;
  } else {
    for (int i = 0; i < 1000 && hdr->magic != kMagic; i++) usleep(1000);
    if (hdr->magic != kMagic) {
      munmap(mem, map_size);
      close(fd);
      return nullptr;
    }
  }
  Store* s = new Store{hdr, base, map_size, fd};
  return s;
}

void rtpu_store_close(Store* s) {
  if (!s) return;
  munmap(s->base, s->map_size);
  close(s->fd);
  delete s;
}

void rtpu_store_destroy(const char* name) { shm_unlink(name); }

// Allocate an object; returns payload offset or 0 (full / exists).
uint64_t rtpu_create(Store* s, const uint8_t* id, uint64_t size) {
  Locker lk(s->hdr);
  Entry* existing = find_slot(s->hdr, id, false);
  if (existing) return 0;  // already present
  uint64_t consumed = 0;
  uint64_t off = heap_alloc(s->hdr, s->base, size, &consumed);
  if (!off) {
    evict_lru(s->hdr, s->base, align_up(size));
    off = heap_alloc(s->hdr, s->base, size, &consumed);
    if (!off) return 0;
  }
  Entry* e = find_slot(s->hdr, id, true);
  if (!e) {  // table full
    heap_free(s->hdr, s->base, off, consumed);
    return 0;
  }
  memcpy(e->id, id, kIdBytes);
  e->state = kCreated;
  e->offset = off;
  e->size = size;
  e->alloc_size = consumed;
  e->refcount = 1;  // writer holds a ref until seal+release
  e->lru_tick = ++s->hdr->lru_clock;
  s->hdr->num_objects++;
  return off;
}

int rtpu_seal(Store* s, const uint8_t* id) {
  Locker lk(s->hdr);
  Entry* e = find_slot(s->hdr, id, false);
  if (!e || e->state != kCreated) return -1;
  e->state = kSealed;
  return 0;
}

// Look up a sealed object; pins it (+1 ref). Returns offset or 0.
uint64_t rtpu_get(Store* s, const uint8_t* id, uint64_t* size_out) {
  Locker lk(s->hdr);
  Entry* e = find_slot(s->hdr, id, false);
  if (!e || e->state != kSealed) return 0;
  e->refcount++;
  e->lru_tick = ++s->hdr->lru_clock;
  if (size_out) *size_out = e->size;
  return e->offset;
}

int rtpu_contains(Store* s, const uint8_t* id) {
  Locker lk(s->hdr);
  Entry* e = find_slot(s->hdr, id, false);
  return (e && e->state == kSealed) ? 1 : 0;
}

int rtpu_release(Store* s, const uint8_t* id) {
  Locker lk(s->hdr);
  Entry* e = find_slot(s->hdr, id, false);
  if (!e || e->state == kTombstone || e->state == kFree) return -1;
  if (e->refcount > 0) e->refcount--;
  e->lru_tick = ++s->hdr->lru_clock;
  if (e->state == kDeleting && e->refcount == 0) {
    heap_free(s->hdr, s->base, e->offset, e->alloc_size);
    e->state = kTombstone;
    s->hdr->num_objects--;
  }
  return 0;
}

// Object lifetime contract (mirrors the driver's object directory): the
// writer ref from rtpu_create is the DIRECTORY's reference and is only
// dropped here, by the owner deciding the object is gone. With that ref
// held, sealed objects are never evictable, so live ObjectRefs can't lose
// data to allocation pressure (finding of the old auto-evict design).
int rtpu_delete(Store* s, const uint8_t* id) {
  Locker lk(s->hdr);
  Entry* e = find_slot(s->hdr, id, false);
  if (!e || e->state == kTombstone || e->state == kFree) return -1;
  if (e->state == kCreated) {
    // Unsealed: the writer is the only possible user; if the owner says
    // delete, the writer is gone (crash recovery path) — free now.
    heap_free(s->hdr, s->base, e->offset, e->alloc_size);
    e->state = kTombstone;
    s->hdr->num_objects--;
    return 0;
  }
  if (e->refcount > 0) e->refcount--;  // drop the writer/directory ref
  if (e->refcount > 0) {
    e->state = kDeleting;  // readers alive: free on their last release
    return 1;
  }
  heap_free(s->hdr, s->base, e->offset, e->alloc_size);
  e->state = kTombstone;
  s->hdr->num_objects--;
  return 0;
}

uint64_t rtpu_evict(Store* s, uint64_t nbytes) {
  Locker lk(s->hdr);
  return evict_lru(s->hdr, s->base, nbytes);
}

void rtpu_stats(Store* s, uint64_t* capacity, uint64_t* used,
                uint64_t* num_objects) {
  Locker lk(s->hdr);
  if (capacity) *capacity = s->hdr->arena_size - s->hdr->heap_start;
  if (used) *used = s->hdr->used_bytes;
  if (num_objects) *num_objects = s->hdr->num_objects;
}

// Fragmentation report for `ray_tpu memory`: walk the free list under the
// arena lock and report block count, total free bytes, and the largest
// contiguous free block (the biggest object the arena can still take
// without eviction).
void rtpu_frag_stats(Store* s, uint64_t* free_blocks, uint64_t* free_bytes,
                     uint64_t* largest_free) {
  Locker lk(s->hdr);
  uint64_t blocks = 0, total = 0, largest = 0;
  uint64_t cur = s->hdr->free_head;
  // the free list is bounded by arena_size/kAlign entries; the guard
  // caps pathological (corrupt-header) walks instead of spinning
  uint64_t guard = s->hdr->arena_size / kAlign + 2;
  while (cur != 0 && blocks < guard) {
    FreeBlock* blk = reinterpret_cast<FreeBlock*>(s->base + cur);
    blocks++;
    total += blk->size;
    if (blk->size > largest) largest = blk->size;
    cur = blk->next;
  }
  if (free_blocks) *free_blocks = blocks;
  if (free_bytes) *free_bytes = total;
  if (largest_free) *largest_free = largest;
}

uint8_t* rtpu_base(Store* s) { return s->base; }

}  // extern "C"
