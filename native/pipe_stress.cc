// Sanitizer stress harness for the native pipe engine (ISSUE 15: the
// store has had a TSAN gate since r5 — this is the same gate for the
// r14 control-pipe transport, built/run under ASan+UBSan AND TSan via
// `make -C native sanitize`).
//
// Phases (each asserts wire-level correctness, not just "no crash", so
// the sanitizers watch the real framing/refpin/overflow code paths):
//   1. kThreads senders hammer one engine pair with pseudo-random-sized
//      pickle-shaped messages (occasional 300 KiB ones to force the
//      partial-write path and multi-recv reassembly) while a single
//      drain thread verifies payload bytes and per-sender ordering.
//   2. sequential RTP1 refpin frames: net borrow table + 0<->1
//      transition records + drain_pins serialization.
//   3. overflow: a record larger than the drain cap must report -needed
//      and survive intact in the overflow queue.
//   4. shutdown from another thread wakes a blocked drain (EOF).
//   5. data-plane: rtpu_copy_mt shard seams + LZ4 roundtrip on random
//      and structured buffers (bounds bugs here are ASan's home turf).

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include <sys/socket.h>
#include <unistd.h>

// Prototypes MUST match pipe.cc exactly (mismatched function types are
// UB that can miscompile under LTO/CFI — defeating a sanitizer gate).
struct NativePipe;
extern "C" {
NativePipe *rtpu_pipe_new(int fd, uint64_t coalesce_us);
int rtpu_pipe_send(NativePipe *p, const uint8_t *buf, uint64_t len);
int64_t rtpu_pipe_drain(NativePipe *p, uint8_t *out, uint64_t cap,
                        uint64_t timeout_ms);
int64_t rtpu_pipe_drain_pins(NativePipe *p, uint8_t *out, uint64_t cap);
void rtpu_pipe_stats(NativePipe *p, uint64_t *out8);
void rtpu_pipe_shutdown(NativePipe *p);
void rtpu_pipe_close(NativePipe *p);
void rtpu_copy_mt(uint8_t *dst, const uint8_t *src, uint64_t n,
                  int threads);
uint64_t rtpu_lz4_bound(uint64_t n);
int64_t rtpu_lz4_compress(const uint8_t *src, uint64_t n, uint8_t *dst,
                          uint64_t cap);
int64_t rtpu_lz4_decompress(const uint8_t *src, uint64_t n, uint8_t *dst,
                            uint64_t dcap);

// The CopyPool and its detached workers are intentionally leaked (see
// pipe.cc: joining them at exit deadlocks in __run_exit_handlers), so
// leak checking would only report designed leaks.
const char *__asan_default_options() { return "detect_leaks=0"; }
}

static const int kThreads = 4;
static const int kIters = 500;
static const int kBigEvery = 97;  // every Nth message is 300 KiB
static const uint64_t kBigSize = 300 * 1024;

#define CHECK(cond, what)                                      \
  do {                                                         \
    if (!(cond)) {                                             \
      std::fprintf(stderr, "FAIL %s:%d %s\n", __FILE__,        \
                   __LINE__, what);                            \
      std::exit(1);                                            \
    }                                                          \
  } while (0)

// Message layout: 0x80 (pickle-protocol marker keeps us off the RTB1/
// RTP1 magics) + u32le thread + u32le seq + pattern byte fill.
static uint64_t msg_size(int t, int i) {
  if (i % kBigEvery == kBigEvery - 1) return kBigSize;
  uint32_t x = static_cast<uint32_t>(t * 2654435761u + i * 40503u + 9);
  return 9 + (x % 4096);
}

static void fill_msg(std::string &m, int t, int i) {
  uint64_t n = msg_size(t, i);
  m.resize(n);
  m[0] = static_cast<char>(0x80);
  uint32_t tv = static_cast<uint32_t>(t), iv = static_cast<uint32_t>(i);
  std::memcpy(&m[1], &tv, 4);
  std::memcpy(&m[5], &iv, 4);
  uint8_t pat = static_cast<uint8_t>(t * 41 + i);
  for (uint64_t k = 9; k < n; ++k) m[k] = static_cast<char>(pat + k);
}

static void check_msg(const uint8_t *d, uint64_t n, int *t_out,
                      int *i_out) {
  CHECK(n >= 9, "record too short");
  CHECK(d[0] == 0x80, "payload lost its pickle marker");
  uint32_t tv, iv;
  std::memcpy(&tv, d + 1, 4);
  std::memcpy(&iv, d + 5, 4);
  CHECK(tv < static_cast<uint32_t>(kThreads), "bad thread field");
  CHECK(n == msg_size(static_cast<int>(tv), static_cast<int>(iv)),
        "record length mismatch");
  uint8_t pat = static_cast<uint8_t>(tv * 41 + iv);
  for (uint64_t k = 9; k < n; ++k)
    CHECK(d[k] == static_cast<uint8_t>(pat + k), "payload corrupted");
  *t_out = static_cast<int>(tv);
  *i_out = static_cast<int>(iv);
}

// Walk packed drain records [u8 type][u32le len][payload]*, invoking
// fn(type, payload, len).
template <typename F>
static void for_each_record(const uint8_t *buf, int64_t n, F fn) {
  int64_t off = 0;
  while (off < n) {
    uint8_t type = buf[off];
    uint32_t len;
    std::memcpy(&len, buf + off + 1, 4);
    CHECK(off + 5 + static_cast<int64_t>(len) <= n,
          "record overruns drain buffer");
    fn(type, buf + off + 5, static_cast<uint64_t>(len));
    off += 5 + len;
  }
  CHECK(off == n, "trailing garbage in drain buffer");
}

static void phase_concurrent_senders(NativePipe *tx, NativePipe *rx) {
  std::vector<std::thread> senders;
  for (int t = 0; t < kThreads; ++t) {
    senders.emplace_back([&, t] {
      std::string m;
      for (int i = 0; i < kIters; ++i) {
        fill_msg(m, t, i);
        CHECK(rtpu_pipe_send(
                  tx, reinterpret_cast<const uint8_t *>(m.data()),
                  m.size()) == 0,
              "send failed mid-stress");
      }
    });
  }

  std::vector<uint8_t> buf(64 * 1024);
  int next_seq[kThreads] = {0, 0, 0, 0};
  uint64_t total = 0, want = static_cast<uint64_t>(kThreads) * kIters;
  while (total < want) {
    int64_t n = rtpu_pipe_drain(rx, buf.data(), buf.size(), 200);
    CHECK(n != -1, "unexpected EOF");
    if (n < -1) {  // a big record needs a bigger buffer
      buf.resize(static_cast<uint64_t>(-n));
      continue;
    }
    for_each_record(buf.data(), n,
                    [&](uint8_t type, const uint8_t *d, uint64_t len) {
                      CHECK(type == 0, "unexpected refpin record");
                      int t, i;
                      check_msg(d, len, &t, &i);
                      // sends from one thread are sequential calls, and
                      // the engine preserves accepted-message order
                      CHECK(i == next_seq[t], "per-sender order broken");
                      next_seq[t]++;
                      total++;
                    });
  }
  for (auto &s : senders) s.join();

  uint64_t st_tx[8], st_rx[8];
  rtpu_pipe_stats(tx, st_tx);
  rtpu_pipe_stats(rx, st_rx);
  CHECK(st_tx[1] == want, "sender message count drifted");
  CHECK(st_rx[4] == want, "receiver message count drifted");
  CHECK(st_tx[0] <= st_tx[1], "more frames than messages");
  std::printf("  phase1 ok: msgs=%llu frames=%llu bytes=%llu\n",
              (unsigned long long)st_tx[1], (unsigned long long)st_tx[0],
              (unsigned long long)st_tx[2]);
}

static void phase_refpins(NativePipe *tx, NativePipe *rx) {
  // Sent sequentially with the socket idle so every frame ships alone
  // (refpin frames are only recognized at top level, never inside a
  // coalesced RTB1 batch — same invariant the Python wrapper relies on).
  uint8_t ida[16], idb[16];
  std::memset(ida, 'a', 16);
  std::memset(idb, 'b', 16);
  const int8_t plan[][2] = {  // {id-is-b, delta}
      {0, +1}, {0, +1}, {1, +1}, {0, -1}, {1, -1}, {0, -1}, {1, +1}};
  for (auto &step : plan) {
    std::string f("RTP1");
    f.append(reinterpret_cast<char *>(step[0] ? idb : ida), 16);
    f.push_back(static_cast<char>(step[1]));
    CHECK(rtpu_pipe_send(tx, reinterpret_cast<const uint8_t *>(f.data()),
                         f.size()) == 0,
          "refpin send failed");
  }
  // expected net transitions: a:+1, b:+1, b:-1, a:-1, b:+1
  const int8_t want_trans[][2] = {{0, +1}, {1, +1}, {1, -1}, {0, -1},
                                  {1, +1}};
  size_t seen = 0;
  std::vector<uint8_t> buf(4096);
  for (int tick = 0; seen < 5; ++tick) {
    CHECK(tick < 40, "refpin transitions never arrived");
    int64_t n = rtpu_pipe_drain(rx, buf.data(), buf.size(), 500);
    CHECK(n >= 0, "unexpected EOF waiting for refpins");
    if (n == 0) continue;  // timeout tick
    for_each_record(
        buf.data(), n, [&](uint8_t type, const uint8_t *d, uint64_t len) {
          CHECK(type == 1, "expected only refpin records here");
          CHECK(len % 17 == 0, "refpin record not 17-byte packed");
          for (uint64_t off = 0; off < len; off += 17) {
            CHECK(seen < 5, "too many transitions");
            const uint8_t *want_id = want_trans[seen][0] ? idb : ida;
            CHECK(std::memcmp(d + off, want_id, 16) == 0,
                  "transition id mismatch");
            CHECK(static_cast<int8_t>(d[off + 16]) ==
                      want_trans[seen][1],
                  "transition sign mismatch");
            seen++;
          }
        });
  }
  // net table: a=0 (erased), b=1
  uint8_t pins[64];
  int64_t n = rtpu_pipe_drain_pins(rx, pins, sizeof(pins));
  CHECK(n == 24, "borrow table should hold exactly one id");
  CHECK(std::memcmp(pins, idb, 16) == 0, "wrong surviving id");
  int64_t count;
  std::memcpy(&count, pins + 16, 8);
  CHECK(count == 1, "wrong surviving count");
  CHECK(rtpu_pipe_drain_pins(rx, pins, sizeof(pins)) == 0,
        "drain_pins must clear the table");
  std::printf("  phase2 ok: refpin transitions + drain_pins verified\n");
}

static void phase_overflow(NativePipe *tx, NativePipe *rx) {
  std::string m;
  fill_msg(m, 1, kBigEvery - 1);  // a 300 KiB message
  CHECK(m.size() == kBigSize, "big fixture sized wrong");
  CHECK(rtpu_pipe_send(tx, reinterpret_cast<const uint8_t *>(m.data()),
                       m.size()) == 0,
        "big send failed");
  uint8_t tiny[512];
  int64_t n;
  do {  // the record may not have fully arrived on the first tick
    n = rtpu_pipe_drain(rx, tiny, sizeof(tiny), 500);
  } while (n == 0);
  CHECK(n == -static_cast<int64_t>(5 + kBigSize),
        "undersized drain must report -(record size)");
  std::vector<uint8_t> big(5 + kBigSize);
  n = rtpu_pipe_drain(rx, big.data(), big.size(), 500);
  CHECK(n == static_cast<int64_t>(5 + kBigSize),
        "retry with exact cap must return the record");
  int t, i;
  CHECK(big[0] == 0, "overflow record type drifted");
  check_msg(big.data() + 5, kBigSize, &t, &i);
  std::printf("  phase3 ok: overflow -needed path verified\n");
}

static void phase_shutdown_wakes_drain() {
  int sv[2];
  CHECK(socketpair(AF_UNIX, SOCK_STREAM, 0, sv) == 0, "socketpair");
  NativePipe *rx = rtpu_pipe_new(sv[0], 0);
  std::atomic<int64_t> result{123456};
  std::thread drainer([&] {
    uint8_t buf[256];
    result.store(rtpu_pipe_drain(rx, buf, sizeof(buf), 10000));
  });
  // give the drain a moment to block in recv, then shut down under it
  std::this_thread::sleep_for(std::chrono::milliseconds(50));
  rtpu_pipe_shutdown(rx);
  drainer.join();
  CHECK(result.load() == -1, "shutdown must surface as drain EOF");
  rtpu_pipe_close(rx);
  ::close(sv[0]);
  ::close(sv[1]);
  std::printf("  phase4 ok: shutdown wakes blocked drain as EOF\n");
}

static void phase_data_plane() {
  // copy_mt: shard seams must be exact for sizes around the 1 MiB
  // single-thread cutoff and non-multiples of the 64 B shard alignment
  const uint64_t sizes[] = {1, 4096, (1u << 20) - 1, (1u << 20) + 1,
                            (4u << 20) + 12345};
  for (uint64_t n : sizes) {
    std::vector<uint8_t> src(n), dst(n, 0);
    for (uint64_t i = 0; i < n; ++i)
      src[i] = static_cast<uint8_t>(i * 131 + 7);
    rtpu_copy_mt(dst.data(), src.data(), n, 4);
    CHECK(std::memcmp(dst.data(), src.data(), n) == 0,
          "copy_mt corrupted bytes");
  }
  // lz4 roundtrip: structured (compressible) and pseudo-random data,
  // including the <13-byte literal-only path
  uint32_t rng = 0x2545f491u;
  for (uint64_t n : {0ull, 5ull, 12ull, 13ull, 4096ull, 262144ull}) {
    for (int mode = 0; mode < 2; ++mode) {
      std::vector<uint8_t> raw(n);
      for (uint64_t i = 0; i < n; ++i) {
        if (mode == 0) {
          raw[i] = static_cast<uint8_t>((i / 64) & 0xff);  // runs
        } else {
          rng ^= rng << 13;
          rng ^= rng >> 17;
          rng ^= rng << 5;
          raw[i] = static_cast<uint8_t>(rng);
        }
      }
      std::vector<uint8_t> comp(rtpu_lz4_bound(n) + 1);
      int64_t c = rtpu_lz4_compress(raw.data(), n, comp.data(),
                                    comp.size());
      CHECK(c >= 0, "compress within bound must succeed");
      std::vector<uint8_t> back(n ? n : 1);
      int64_t d = rtpu_lz4_decompress(comp.data(),
                                      static_cast<uint64_t>(c),
                                      back.data(), n);
      CHECK(d == static_cast<int64_t>(n), "roundtrip length mismatch");
      CHECK(n == 0 || std::memcmp(back.data(), raw.data(), n) == 0,
            "roundtrip bytes mismatch");
    }
  }
  // malformed input must fail cleanly, not read out of bounds
  const uint8_t evil[] = {0x1f, 0x41, 0x41, 0x41, 0xff, 0xff};
  uint8_t out[64];
  CHECK(rtpu_lz4_decompress(evil, sizeof(evil), out, sizeof(out)) == -1,
        "malformed block must return -1");
  std::printf("  phase5 ok: copy_mt + lz4 roundtrips verified\n");
}

int main() {
  int sv[2];
  CHECK(socketpair(AF_UNIX, SOCK_STREAM, 0, sv) == 0, "socketpair");
  NativePipe *tx = rtpu_pipe_new(sv[0], 0);
  NativePipe *rx = rtpu_pipe_new(sv[1], 0);

  phase_concurrent_senders(tx, rx);
  phase_refpins(tx, rx);
  phase_overflow(tx, rx);

  rtpu_pipe_close(tx);
  rtpu_pipe_close(rx);
  ::close(sv[0]);
  ::close(sv[1]);

  phase_shutdown_wakes_drain();
  phase_data_plane();

  std::printf("pipe-stress ok: %d senders x %d msgs\n", kThreads, kIters);
  return 0;
}
