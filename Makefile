# Developer entry points. (The native store has its own Makefile under
# native/; `make -C native`.)

PY ?= python
NATIVE_SRCS := $(wildcard native/*.cc)

.PHONY: lint lint-native lint-fix-docs check test native native-sanitize

# graftlint over the package (all 9 families, including the
# whole-program protocol/lifecycle/lockgraph stage). Runs the
# standalone launcher under -S: skips the axon sitecustomize's ~1.9 s
# jax import AND the ray_tpu package __init__, so a warm run (model
# cache under .graftlint_cache/) stays under ~1.5 s on this box.
lint:
	$(PY) -S ray_tpu/devtools/graftlint/standalone.py

# compiler-as-linter over the native plane: syntax + warnings only,
# no objects produced (the real build is `make -C native`)
lint-native:
	$(CXX) -std=c++17 -fsyntax-only -Wall -Wextra $(NATIVE_SRCS)

# regenerate the README rule catalog after adding/changing rules
lint-fix-docs:
	$(PY) -S ray_tpu/devtools/graftlint/standalone.py --update README.md

# everything a PR must pass locally, cheapest first
check: lint lint-native test

test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow'

native:
	$(MAKE) -C native

# ASan/UBSan + TSan variants of the native plane plus the stress
# harnesses (see native/Makefile `sanitize`)
native-sanitize:
	$(MAKE) -C native sanitize
