# Developer entry points. (The native store has its own Makefile under
# native/; `make -C native`.)

PY ?= python

.PHONY: lint lint-fix-docs test native

# graftlint over the package: pure-ast, no jax import, <10 s on this box.
# JAX_PLATFORMS=cpu is belt-and-braces for the axon sitecustomize (the
# CLI also pins an already-imported jax to cpu before any device query).
lint:
	JAX_PLATFORMS=cpu $(PY) -m ray_tpu.devtools.graftlint

# regenerate the README rule catalog after adding/changing rules
lint-fix-docs:
	JAX_PLATFORMS=cpu $(PY) -m ray_tpu.devtools.graftlint --update README.md

test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow'

native:
	$(MAKE) -C native
