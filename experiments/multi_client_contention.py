"""Root-cause the multi-client microbench inversion (ISSUE 4).

BENCH r5: multi-client tasks 1,940/s vs 4,776/s single-client; worker
puts 3.12 GB/s aggregate vs 7.37 GB/s driver-local — where the
reference SCALES UP ~3x with extra clients. This experiment reruns the
bench's multi-client sections under the new core instrumentation and
attributes the gap between three suspects:

  (a) driver dispatch-lock contention  -> rtpu_lock_wait_seconds /
      summarize_contention deltas per section;
  (b) per-task control-plane work growth (extra pipe messages: specs,
      refpins, get waiters ship from client workers) -> pipe
      message/byte deltas per task;
  (c) plain CPU saturation (2 vCPUs run driver + 2 clients + 4 pool
      workers) -> process CPU time vs wall time per section.

Run: JAX_PLATFORMS=cpu python experiments/multi_client_contention.py
Prints one JSON object; append findings to CHANGES.md.
"""

import json
import os
import resource
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

import ray_tpu  # noqa: E402
from ray_tpu.util import contention  # noqa: E402
from ray_tpu.util.metrics import registry_records  # noqa: E402


def _counter(name, tags=None):
    total = 0.0
    for rec in registry_records():
        if rec["name"] != name:
            continue
        want = tuple((tags or {}).items())
        for key, val in rec["samples"]:
            if all(t in key for t in want):
                total += val if not isinstance(val, tuple) else val[2]
    return total


class Section:
    """Deltas of contention stats, pipe counters, and CPU time around a
    measured section."""

    def __init__(self, name):
        self.name = name

    def __enter__(self):
        contention.reset()
        self.t0 = time.perf_counter()
        r = resource.getrusage(resource.RUSAGE_SELF)
        self.cpu0 = r.ru_utime + r.ru_stime
        self.msgs0 = (_counter("rtpu_pipe_messages_total",
                               {"direction": "sent"})
                      + _counter("rtpu_pipe_messages_total",
                                 {"direction": "recv"}))
        self.bytes0 = (_counter("rtpu_pipe_sent_bytes_total")
                       + _counter("rtpu_pipe_recv_bytes_total"))
        return self

    def __exit__(self, *exc):
        self.wall = time.perf_counter() - self.t0
        r = resource.getrusage(resource.RUSAGE_SELF)
        self.cpu = r.ru_utime + r.ru_stime - self.cpu0
        self.msgs = (_counter("rtpu_pipe_messages_total",
                              {"direction": "sent"})
                     + _counter("rtpu_pipe_messages_total",
                                {"direction": "recv"})) - self.msgs0
        self.bytes = (_counter("rtpu_pipe_sent_bytes_total")
                      + _counter("rtpu_pipe_recv_bytes_total")
                      ) - self.bytes0
        self.locks = {k: v for k, v in contention.summarize().items()
                      if v["wait_total_s"] > 0.0005}

    def report(self, n_tasks=None):
        out = {"wall_s": round(self.wall, 3),
               "driver_cpu_s": round(self.cpu, 3),
               "driver_cpu_frac": round(self.cpu / self.wall, 3),
               "pipe_msgs": int(self.msgs),
               "pipe_bytes": int(self.bytes),
               "lock_waits": self.locks}
        if n_tasks:
            out["rate_per_s"] = round(n_tasks / self.wall, 1)
            out["pipe_msgs_per_task"] = round(self.msgs / n_tasks, 2)
            out["driver_cpu_us_per_task"] = round(
                self.cpu / n_tasks * 1e6, 1)
        return out


def main():
    ray_tpu.init(num_cpus=4)
    out = {"loadavg_start": os.getloadavg()}

    @ray_tpu.remote
    def noop():
        return None

    for _ in range(3):  # steady-state pool
        ray_tpu.get([noop.remote() for _ in range(60)])

    # -- A: single-client task throughput --------------------------------
    n = 600
    best = None
    for _ in range(3):
        with Section("single") as s:
            ray_tpu.get([noop.remote() for _ in range(n)])
        rep = s.report(n)
        if best is None or rep["rate_per_s"] > best["rate_per_s"]:
            best = rep
    out["single_client_tasks"] = best

    # -- B: multi-client (bench shape: 2 actor clients x 250 noops) ------
    @ray_tpu.remote
    class BatchClient:
        def small_value_batch(self, n):
            ray_tpu.get([noop.remote() for _ in range(n)])
            return n

    clients = [BatchClient.remote() for _ in range(2)]
    ray_tpu.get([c.small_value_batch.remote(10) for c in clients])
    best = None
    for _ in range(3):
        with Section("multi") as s:
            ray_tpu.get([c.small_value_batch.remote(250)
                         for c in clients])
        rep = s.report(500)
        if best is None or rep["rate_per_s"] > best["rate_per_s"]:
            best = rep
    out["multi_client_tasks"] = best

    # -- B2: clients at num_cpus=0 (slot-starvation control: with 1-CPU
    # clients only 2 of 4 CPU slots remain for noops) ---------------------
    zclients = [BatchClient.options(num_cpus=0).remote()
                for _ in range(2)]
    ray_tpu.get([c.small_value_batch.remote(10) for c in zclients])
    best = None
    for _ in range(3):
        with Section("multi0") as s:
            ray_tpu.get([c.small_value_batch.remote(250)
                         for c in zclients])
        rep = s.report(500)
        if best is None or rep["rate_per_s"] > best["rate_per_s"]:
            best = rep
    out["multi_client_tasks_cpus0"] = best
    for c in clients + zclients:
        ray_tpu.kill(c)

    # -- C: put bandwidth, driver-local vs worker-side -------------------
    arr = np.zeros((8 << 20) // 8)

    best = None
    for _ in range(3):
        with Section("put_local") as s:
            for _ in range(8):
                ray_tpu.put(arr)
        gbs = 8 * arr.nbytes / s.wall / 1e9
        if best is None or gbs > best["gb_per_s"]:
            best = {"gb_per_s": round(gbs, 2), **s.report()}
    out["put_driver_local"] = best

    @ray_tpu.remote
    def do_put(nbytes, times):
        data = np.zeros(nbytes // 8)
        for _ in range(times):
            ray_tpu.put(data)
        return times * nbytes

    ray_tpu.get(do_put.remote(1 << 16, 1))
    best = None
    for _ in range(3):
        with Section("put_multi") as s:
            ray_tpu.get([do_put.remote(8 << 20, 4) for _ in range(2)])
        gbs = 2 * 4 * (8 << 20) / s.wall / 1e9
        if best is None or gbs > best["gb_per_s"]:
            best = {"gb_per_s": round(gbs, 2), **s.report()}
    out["put_worker_multi"] = best

    # task-phase percentiles for the whole run (queue vs lease vs exec)
    from ray_tpu.util.state import summarize_tasks

    phases = summarize_tasks().get("noop", {}).get("phases", {})
    out["noop_phases_ms"] = {k: {"p50": v["p50_ms"], "p99": v["p99_ms"]}
                             for k, v in phases.items()}

    # -- D: trace-plane critical path (ISSUE 7 acceptance): rerun the
    # multi-client shape with tracing armed and let the per-task segment
    # breakdown say where the wall time goes — the r8 root cause
    # (GIL-serialized driver control-plane CPU) should print as the
    # dominant driver_submit/transit share, from trace data alone.
    from ray_tpu.util import tracing
    from ray_tpu.util.state import summarize_critical_path
    from ray_tpu.util.trace_store import format_breakdown

    tracing.enable_tracing()
    tclients = [BatchClient.options(num_cpus=0).remote()
                for _ in range(2)]
    ray_tpu.get([c.small_value_batch.remote(10) for c in tclients])
    ray_tpu.get([c.small_value_batch.remote(250) for c in tclients])
    time.sleep(2.0)  # let the worker span pushes drain
    cp = summarize_critical_path()
    out["critical_path"] = cp
    tracing.disable_tracing()
    for c in tclients:
        ray_tpu.kill(c)

    # -- E: profiling-plane driver attribution (ISSUE 9 acceptance):
    # run state.profile(seconds=2) DURING the multi-client shape and let
    # the merged samples name the control-plane functions the driver
    # burns its GIL-serialized CPU in (submit / pipe send / refpin
    # paths by self-time) — the direct input to ROADMAP item 1.
    import sys
    import threading

    from ray_tpu.util import state as _state

    pclients = [BatchClient.options(num_cpus=0).remote()
                for _ in range(2)]
    ray_tpu.get([c.small_value_batch.remote(10) for c in pclients])
    done = threading.Event()

    def _drive():
        try:
            while not done.is_set():
                ray_tpu.get([c.small_value_batch.remote(250)
                             for c in pclients], timeout=120)
        except Exception:
            pass

    driver_thread = threading.Thread(target=_drive, daemon=True)
    driver_thread.start()
    prof = _state.profile(seconds=2.0)
    done.set()
    driver_thread.join(timeout=120)
    for c in pclients:
        ray_tpu.kill(c)
    top_driver = (prof.get("top_self_by_component") or {}).get(
        "driver", [])
    out["profile"] = {
        "total_samples": prof["total_samples"],
        "idle_samples": prof["idle_samples"],
        "processes": len(prof["processes"]),
        "top_driver_self": top_driver[:12],
    }
    print("§E driver control-plane self-time "
          f"({prof['total_samples']} busy samples, "
          f"{len(prof['processes'])} processes):", file=sys.stderr)
    for row in top_driver[:12]:
        print(f"  {row['self_pct']:5.1f}%  {row['self_samples']:>6}  "
              f"{row['function']}", file=sys.stderr)

    out["loadavg_end"] = os.getloadavg()
    ray_tpu.shutdown()
    print(format_breakdown(cp), file=sys.stderr)
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
