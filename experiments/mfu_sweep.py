"""On-chip MFU sweep: find the best (remat, batch, attention) config.

Round-4 context: the first real-TPU bench (batch 4, remat off, blockwise
XLA fallback after the batch-16 no-remat program OOMed 31G/15.75G HBM)
measured 0.143 MFU. This sweep runs each candidate config in a fresh
child process (OOM isolation + clean backend claim) and prints one JSON
line per config, so bench.py's defaults can be set from measurements
instead of guesses.

Usage:  JAX_PLATFORMS=axon python experiments/mfu_sweep.py            # all
        JAX_PLATFORMS=axon python experiments/mfu_sweep.py --child '{...}'
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CONFIGS = [
    # (name, remat, remat_policy, batch, attn_impl, loss_chunk, env[, seq])
    # round-4 sweep 1 results (no loss_chunk): remat_full_b16_pallas
    # 0.2027 MFU / remat_attn_b16 0.1968 / remat_attn_b8 0.1947 /
    # remat_full_b16_xla 0.1078; b32 and no-remat b8 died in the remote
    # compile helper (HTTP 500 — retried once in-child now).
    ("remat_full_b32_chunk512", True, "full", 32, "pallas", 512, {}),
    ("remat_full_b16_chunk512", True, "full", 16, "pallas", 512, {}),
    ("remat_attn_b32_chunk512", True, "save_attn", 32, "pallas", 512, {}),
    ("remat_attn_b16_chunk512", True, "save_attn", 16, "pallas", 512, {}),
    ("remat_full_b64_chunk512", True, "full", 64, "pallas", 512, {}),
    ("remat_full_b16_pallas", True, "full", 16, "pallas", 0, {}),
    # flash tile sweep (at the best batch/chunk point)
    ("b32_chunk_blk256", True, "full", 32, "pallas", 512,
     {"RTPU_ATTN_BLOCK_Q": "256", "RTPU_ATTN_BLOCK_K": "256"}),
    ("b32_chunk_blk1024", True, "full", 32, "pallas", 512,
     {"RTPU_ATTN_BLOCK_Q": "1024", "RTPU_ATTN_BLOCK_K": "1024"}),
    ("b32_chunk_blkq1024k512", True, "full", 32, "pallas", 512,
     {"RTPU_ATTN_BLOCK_Q": "1024", "RTPU_ATTN_BLOCK_K": "512"}),
    # scoped-vmem variants: the r4 b32 compile-helper failures are the
    # kind --xla_tpu_scoped_vmem_limit_kib moves (VERDICT r4 #1). Via
    # per-jit compiler_options (RTPU_ knob), NOT XLA_FLAGS: TPU flags in
    # XLA_FLAGS abort the HOST flag parser on the axon backend (the r5
    # sweep-1 rc=1 failures).
    ("b32_chunk_vmem64m", True, "full", 32, "pallas", 512,
     {"RTPU_XLA_COMPILER_OPTIONS": "xla_tpu_scoped_vmem_limit_kib=65536"}),
    ("b32_chunk_vmem16m", True, "full", 32, "pallas", 512,
     {"RTPU_XLA_COMPILER_OPTIONS": "xla_tpu_scoped_vmem_limit_kib=16384"}),
    # longer sequence at constant tokens/step: more attention FLOPs per
    # token, fewer lm-head+embed passes per token
    ("seq4096_b16_chunk512", True, "full", 16, "pallas", 512, {}, 4096),
    ("seq4096_b8_chunk512", True, "full", 8, "pallas", 512, {}, 4096),
    # no-remat retry: the r5 bf16-residual custom VJPs (rms/layer norm +
    # rotary, ops/layers.py) kill the f32 [B,L,D] residuals that OOMed
    # r4's no-remat runs. No remat = no recompute = the single biggest
    # MFU lever if it fits (full-remat pays ~1.33x FLOPs).
    ("noremat_b8_chunk512", False, "full", 8, "pallas", 512, {}),
    ("noremat_b16_chunk512", False, "full", 16, "pallas", 512, {}),
    ("noremat_b32_chunk512", False, "full", 32, "pallas", 512, {}),
    # blk1024 tiles won sweep 2 (0.2463 vs 0.2134 at the default 512):
    # the flash kernel is ~2x end-to-end, so tile shape is the dominant
    # knob. Cross it with batch and the no-remat path.
    ("b16_chunk_blk1024", True, "full", 16, "pallas", 512,
     {"RTPU_ATTN_BLOCK_Q": "1024", "RTPU_ATTN_BLOCK_K": "1024"}),
    ("b64_chunk_blk1024", True, "full", 64, "pallas", 512,
     {"RTPU_ATTN_BLOCK_Q": "1024", "RTPU_ATTN_BLOCK_K": "1024"}),
    ("noremat_b8_blk1024", False, "full", 8, "pallas", 512,
     {"RTPU_ATTN_BLOCK_Q": "1024", "RTPU_ATTN_BLOCK_K": "1024"}),
    ("noremat_b16_blk1024", False, "full", 16, "pallas", 512,
     {"RTPU_ATTN_BLOCK_Q": "1024", "RTPU_ATTN_BLOCK_K": "1024"}),
    ("noremat_b32_blk1024", False, "full", 32, "pallas", 512,
     {"RTPU_ATTN_BLOCK_Q": "1024", "RTPU_ATTN_BLOCK_K": "1024"}),
    ("seq4096_b16_blk1024", True, "full", 16, "pallas", 512,
     {"RTPU_ATTN_BLOCK_Q": "1024", "RTPU_ATTN_BLOCK_K": "1024"}, 4096),
]


def child(cfg: dict) -> None:
    sys.path.insert(0, _REPO)
    from ray_tpu.util.tpu_info import honor_jax_platform_env

    honor_jax_platform_env()
    import jax
    import numpy as np
    import optax

    from ray_tpu import models
    from ray_tpu.ops.attention import set_default_attention_impl
    from ray_tpu.parallel import MeshConfig
    from ray_tpu.train import TrainLoopHelper
    from ray_tpu.util.tpu_info import peak_flops_per_chip

    out = {"name": cfg["name"], "ok": False, "cfg": cfg}

    def attempt():
        set_default_attention_impl(cfg["attn"])
        config = models.get_config(cfg.get("model", "llama-250m")).replace(
            remat=cfg["remat"], remat_policy=cfg["policy"],
            loss_chunk=cfg.get("loss_chunk", 0))
        seq, batch_size = cfg.get("seq", 2048), cfg["batch"]
        helper = TrainLoopHelper.create(
            lambda: models.init_params(jax.random.PRNGKey(0), config),
            models.param_axes(config),
            lambda p, b: models.loss_and_metrics(p, b, config),
            optax.adamw(1e-4),
            mesh_config=MeshConfig(dp=1, fsdp=-1, tp=1, sp=1),
        )
        rng = np.random.default_rng(0)
        toks = rng.integers(0, config.vocab_size, size=(batch_size, seq + 1),
                            dtype=np.int32)
        batch = {"inputs": toks[:, :-1], "targets": toks[:, 1:]}
        iters = 10
        t0 = time.perf_counter()
        m = helper.run_steps(batch, iters)  # compile + warm
        float(jax.device_get(m["loss"]))
        out["compile_warmup_s"] = round(time.perf_counter() - t0, 1)
        t0 = time.perf_counter()
        m = helper.run_steps(batch, iters)
        float(jax.device_get(m["loss"]))
        dt = (time.perf_counter() - t0) / iters
        tokens_per_sec = batch_size * seq / dt
        flops_token = config.flops_per_token() + (
            6 * config.n_layers * config.hdim * config.n_heads * seq)
        mfu = flops_token * tokens_per_sec / peak_flops_per_chip()
        out.update(ok=True, step_ms=round(dt * 1e3, 2),
                   tokens_per_sec=round(tokens_per_sec, 1),
                   mfu=round(mfu, 4),
                   backend=jax.default_backend())

    # The r4 sweep lost two configs to one-off remote-compile HTTP 500s
    # (the axon compile-helper subprocess died); that path is stateless,
    # so one in-child retry is cheap. The loop (vs a nested except) lets
    # the first attempt's traceback — which pins the on-device params +
    # opt state — be dropped before the retry allocates its own.
    for attempt_no in range(2):
        err = None
        try:
            attempt()
            break
        except Exception as e:
            err = f"{type(e).__name__}: {str(e)[:300]}"
            retryable = "remote_compile" in str(e) or "INTERNAL" in str(e)
        if attempt_no == 0 and retryable:
            out["retried_after"] = err
            time.sleep(5)
            continue
        out["error"] = err
        break
    print(json.dumps(out))


MAX_ATTEMPTS = 2        # deterministic failures (OOM, Mosaic reject)
MAX_ANY_ATTEMPTS = 4    # all failures incl. timeouts/tunnel flakes

_DETERMINISTIC = ("RESOURCE_EXHAUSTED", "Allocation", "Mosaic",
                  "NotImplementedError", "ValueError")


def _scan_records(path: str) -> list:
    recs = []
    try:
        with open(path) as f:
            for line in f:
                try:
                    recs.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
    except OSError:
        pass
    return recs


def _done_names(path: str) -> set:
    """Configs to skip: measured ok, failed MAX_ATTEMPTS times with a
    deterministic error (an OOM must not busy-loop the watcher), or failed
    MAX_ANY_ATTEMPTS times with anything (a repeatedly hanging compile is
    not worth a fifth window). Tunnel-death failures are mostly filtered
    at the source — the runner aborts instead of logging a failure when a
    post-failure probe finds the tunnel down."""
    ok, det_fails, any_fails = set(), {}, {}
    for rec in _scan_records(path):
        name = rec.get("name")
        if rec.get("ok"):
            ok.add(name)
        else:
            any_fails[name] = any_fails.get(name, 0) + 1
            err = str(rec.get("error", ""))
            if any(s in err for s in _DETERMINISTIC):
                det_fails[name] = det_fails.get(name, 0) + 1
    return (ok
            | {n for n, c in det_fails.items() if c >= MAX_ATTEMPTS}
            | {n for n, c in any_fails.items() if c >= MAX_ANY_ATTEMPTS})


def _tunnel_alive(timeout: float = 25.0) -> bool:
    """Cheap child-process device query (same contract as tpu_watch.probe)."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; jax.config.update('jax_platforms', 'axon'); "
             "print('NDEV', len(jax.devices()))"],
            capture_output=True, text=True, timeout=timeout,
            env=dict(os.environ))
        return proc.returncode == 0 and "NDEV" in proc.stdout
    except Exception:
        return False


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--child", default=None)
    ap.add_argument("--only", default=None,
                    help="comma-separated config-name filter")
    ap.add_argument("--out", default=None,
                    help="append each result record to this jsonl file")
    ap.add_argument("--skip-ok", action="store_true",
                    help="skip configs already ok (or failed MAX_ATTEMPTS "
                         "times) in --out — resumable across tunnel windows")
    ap.add_argument("--timeout", type=float, default=900.0)
    args = ap.parse_args()
    if args.child:
        child(json.loads(args.child))
        return 0
    done = _done_names(args.out) if (args.skip_ok and args.out) else set()
    results = []
    for row in CONFIGS:
        (name, remat, policy, batch, attn, loss_chunk, extra_env) = row[:7]
        seq = row[7] if len(row) > 7 else 2048
        if args.only and name not in args.only.split(","):
            continue
        if name in done:
            continue
        cfg = {"name": name, "remat": remat, "policy": policy,
               "batch": batch, "attn": attn, "loss_chunk": loss_chunk,
               "seq": seq, "env": extra_env}
        env = dict(os.environ)
        for k, v in extra_env.items():
            # merge (not clobber) composite flag vars the caller may have set
            env[k] = (env[k] + " " + v) if (k == "XLA_FLAGS" and k in env) else v
        env["JAX_PLATFORMS"] = "axon"
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--child", json.dumps(cfg)],
                capture_output=True, text=True, timeout=args.timeout,
                env=env, cwd=_REPO)
            line = next((ln for ln in reversed(proc.stdout.splitlines())
                         if ln.startswith("{")), None)
            rec = (json.loads(line) if line else
                   {"name": name, "ok": False, "cfg": cfg,
                    "error": f"rc={proc.returncode}: {proc.stderr[-400:]}"})
        except subprocess.TimeoutExpired:
            rec = {"name": name, "ok": False, "cfg": cfg,
                   "error": f"timeout {args.timeout:.0f}s"}
        if not rec.get("ok") and not _tunnel_alive():
            # the failure is (probably) the tunnel dying, not the config —
            # stop the sweep. Still charge ONE non-deterministic failure:
            # it won't count toward MAX_ATTEMPTS retirement, but the
            # MAX_ANY_ATTEMPTS backstop must see configs whose failure
            # wedges the chip itself, or the first such config would be
            # retried first in every window forever, starving the rest.
            rec = {"name": name, "ok": False, "cfg": cfg,
                   "error": f"aborted, tunnel down after: {rec.get('error', '?')[:200]}"}
            print(json.dumps(rec), flush=True)
            if args.out:
                with open(args.out, "a") as f:
                    f.write(json.dumps(rec) + "\n")
            break
        results.append(rec)
        print(json.dumps(rec), flush=True)
        if args.out:
            with open(args.out, "a") as f:
                f.write(json.dumps(rec) + "\n")
    best = max((r for r in results if r.get("ok")),
               key=lambda r: r.get("mfu", 0), default=None)
    print(json.dumps({"best": best}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
