"""On-chip MFU sweep: find the best (remat, batch, attention) config.

Round-4 context: the first real-TPU bench (batch 4, remat off, blockwise
XLA fallback after the batch-16 no-remat program OOMed 31G/15.75G HBM)
measured 0.143 MFU. This sweep runs each candidate config in a fresh
child process (OOM isolation + clean backend claim) and prints one JSON
line per config, so bench.py's defaults can be set from measurements
instead of guesses.

Usage:  JAX_PLATFORMS=axon python experiments/mfu_sweep.py            # all
        JAX_PLATFORMS=axon python experiments/mfu_sweep.py --child '{...}'
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CONFIGS = [
    # (name, remat, remat_policy, batch, attn_impl, loss_chunk, env)
    # round-4 sweep 1 results (no loss_chunk): remat_full_b16_pallas
    # 0.2027 MFU / remat_attn_b16 0.1968 / remat_attn_b8 0.1947 /
    # remat_full_b16_xla 0.1078; b32 and no-remat b8 OOMed.
    ("remat_full_b32_chunk512", True, "full", 32, "pallas", 512, {}),
    ("remat_full_b16_chunk512", True, "full", 16, "pallas", 512, {}),
    ("remat_attn_b32_chunk512", True, "save_attn", 32, "pallas", 512, {}),
    ("remat_full_b64_chunk512", True, "full", 64, "pallas", 512, {}),
    ("remat_full_b16_pallas", True, "full", 16, "pallas", 0, {}),
    # flash tile sweep (at the best batch/chunk point)
    ("b32_chunk_blk256", True, "full", 32, "pallas", 512,
     {"RTPU_ATTN_BLOCK_Q": "256", "RTPU_ATTN_BLOCK_K": "256"}),
    ("b32_chunk_blk1024", True, "full", 32, "pallas", 512,
     {"RTPU_ATTN_BLOCK_Q": "1024", "RTPU_ATTN_BLOCK_K": "1024"}),
    ("b32_chunk_blkq1024k512", True, "full", 32, "pallas", 512,
     {"RTPU_ATTN_BLOCK_Q": "1024", "RTPU_ATTN_BLOCK_K": "512"}),
]


def child(cfg: dict) -> None:
    sys.path.insert(0, _REPO)
    from ray_tpu.util.tpu_info import honor_jax_platform_env

    honor_jax_platform_env()
    import jax
    import numpy as np
    import optax

    from ray_tpu import models
    from ray_tpu.ops.attention import set_default_attention_impl
    from ray_tpu.parallel import MeshConfig
    from ray_tpu.train import TrainLoopHelper
    from ray_tpu.util.tpu_info import peak_flops_per_chip

    out = {"name": cfg["name"], "ok": False}
    try:
        set_default_attention_impl(cfg["attn"])
        config = models.llama_250m().replace(
            remat=cfg["remat"], remat_policy=cfg["policy"],
            loss_chunk=cfg.get("loss_chunk", 0))
        seq, batch_size = 2048, cfg["batch"]
        helper = TrainLoopHelper.create(
            lambda: models.init_params(jax.random.PRNGKey(0), config),
            models.param_axes(config),
            lambda p, b: models.loss_and_metrics(p, b, config),
            optax.adamw(1e-4),
            mesh_config=MeshConfig(dp=1, fsdp=-1, tp=1, sp=1),
        )
        rng = np.random.default_rng(0)
        toks = rng.integers(0, config.vocab_size, size=(batch_size, seq + 1),
                            dtype=np.int32)
        batch = {"inputs": toks[:, :-1], "targets": toks[:, 1:]}
        iters = 10
        t0 = time.perf_counter()
        m = helper.run_steps(batch, iters)  # compile + warm
        float(jax.device_get(m["loss"]))
        out["compile_warmup_s"] = round(time.perf_counter() - t0, 1)
        t0 = time.perf_counter()
        m = helper.run_steps(batch, iters)
        float(jax.device_get(m["loss"]))
        dt = (time.perf_counter() - t0) / iters
        tokens_per_sec = batch_size * seq / dt
        flops_token = config.flops_per_token() + (
            6 * config.n_layers * config.hdim * config.n_heads * seq)
        mfu = flops_token * tokens_per_sec / peak_flops_per_chip()
        out.update(ok=True, step_ms=round(dt * 1e3, 2),
                   tokens_per_sec=round(tokens_per_sec, 1),
                   mfu=round(mfu, 4),
                   backend=jax.default_backend())
    except Exception as e:
        out["error"] = f"{type(e).__name__}: {str(e)[:300]}"
    print(json.dumps(out))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--child", default=None)
    ap.add_argument("--only", default=None,
                    help="comma-separated config-name filter")
    args = ap.parse_args()
    if args.child:
        child(json.loads(args.child))
        return 0
    results = []
    for (name, remat, policy, batch, attn, loss_chunk, extra_env) in CONFIGS:
        if args.only and name not in args.only.split(","):
            continue
        cfg = {"name": name, "remat": remat, "policy": policy,
               "batch": batch, "attn": attn, "loss_chunk": loss_chunk}
        env = dict(os.environ)
        env.update(extra_env)
        env["JAX_PLATFORMS"] = "axon"
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--child", json.dumps(cfg)],
                capture_output=True, text=True, timeout=900, env=env,
                cwd=_REPO)
            line = next((ln for ln in reversed(proc.stdout.splitlines())
                         if ln.startswith("{")), None)
            rec = (json.loads(line) if line else
                   {"name": name, "ok": False,
                    "error": f"rc={proc.returncode}: {proc.stderr[-400:]}"})
        except subprocess.TimeoutExpired:
            rec = {"name": name, "ok": False, "error": "timeout 900s"}
        results.append(rec)
        print(json.dumps(rec), flush=True)
    best = max((r for r in results if r.get("ok")),
               key=lambda r: r.get("mfu", 0), default=None)
    print(json.dumps({"best": best}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
