"""Traffic-replay load generator for the LLM serving tier (ISSUE 12/13).

Replays a synthetic multi-tenant trace — a shared-prefix mixture (each
tenant has a fixed system prompt; its requests append distinct user
suffixes) with bursty on/off arrivals, optionally salted with periodic
LONG prompts (the disaggregation stressor: a long prefill arriving
during steady decode) — against one of:

- an in-process :class:`~ray_tpu.serve.llm.LLMEngine` (the
  same-container A/B mode ``bench.py``'s ``serve_llm`` section uses);
- an in-process colocated-vs-disaggregated engine PAIR
  (``--disagg``; ``bench.py``'s ``serve_disagg`` section);
- a deployed multi-replica application (``--serve``), optionally
  through a multi-node cluster (``--nodes N``) and optionally split
  into prefill/decode pools (``--serve --disagg``).

The trace is GENERATED AS A STREAM (O(1) memory per in-flight request)
and the stats keep bounded reservoirs, so ``--scale full`` (>= 1M
requests — the ROADMAP's millions-of-users envelope) runs in bounded
memory; the envelope is the cluster's, not the harness's. Reports the
serving-tier scorecard:

    tokens/s (generated), TTFT p50/p99, TPOT p50/p99,
    prefix-cache hit rate, shed rate, error count,
    SLO verdict + per-pool KV-leak audit (serve modes)

Prints ONE JSON line (the bench.py contract).
"""

from __future__ import annotations

import argparse
import hashlib as _hash
import json

import os
import sys
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import (Any, Callable, Dict, Iterable, Iterator, List,
                    Optional, Tuple)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:  # runnable as `python experiments/serve_replay.py`
    sys.path.insert(0, _REPO)


# ---------------------------------------------------------------------------
# trace generation (streamed: --scale full must not materialize 1M requests)
# ---------------------------------------------------------------------------

@dataclass
class TraceConfig:
    n_requests: int = 200
    n_tenants: int = 4
    shared_prefix_tokens: int = 48     # per-tenant system prompt length
    suffix_tokens_mean: int = 12       # user-suffix length (geometric-ish)
    max_new_tokens: int = 16
    vocab: int = 256
    # bursty arrivals: ON periods at burst_rps, OFF gaps between bursts
    burst_rps: float = 50.0
    burst_len_s: float = 0.5
    gap_s: float = 0.25
    seed: int = 0
    # mixed-workload salt (ISSUE 13): every Nth request carries a LONG
    # prompt — the arrival pattern that makes colocated decode cadence
    # collapse and disaggregation win. 0 disables.
    long_every: int = 0
    long_prompt_tokens: int = 0
    # multi-model salt (ISSUE 16): each request addresses one of
    # n_models models, drawn Zipf(zipf_alpha) — the skew that makes
    # multiplexing win (the hot model spreads over every replica while
    # dedicated deployments strand their cold engines). 0 disables.
    n_models: int = 0
    zipf_alpha: float = 1.5


@dataclass
class Request:
    arrival_s: float
    tenant: int
    prompt: List[int]
    max_new: int
    model_id: Optional[str] = None


def iter_trace(cfg: TraceConfig) -> Iterator[Request]:
    """Deterministic multi-tenant trace, yielded one request at a time:
    tenant system prompts are fixed per seed; arrivals are an on/off
    burst process (the shape that separates load-aware routing from
    round-robin — bursts pile onto whichever replica round-robin happens
    to hit mid-burst). O(tenants) state regardless of n_requests."""
    import numpy as np

    rng = np.random.default_rng(cfg.seed)
    prefixes = [rng.integers(0, cfg.vocab, cfg.shared_prefix_tokens)
                .tolist() for _ in range(cfg.n_tenants)]
    model_p = None
    if cfg.n_models > 0:
        w = np.array([1.0 / (r + 1) ** cfg.zipf_alpha
                      for r in range(cfg.n_models)])
        model_p = w / w.sum()
    t = 0.0
    in_burst_left = cfg.burst_len_s
    for i in range(cfg.n_requests):
        # exponential inter-arrival inside a burst; jump the gap when the
        # burst budget is spent
        dt = float(rng.exponential(1.0 / cfg.burst_rps))
        in_burst_left -= dt
        if in_burst_left <= 0:
            t += cfg.gap_s
            in_burst_left = cfg.burst_len_s
        t += dt
        tenant = int(rng.integers(cfg.n_tenants))
        if cfg.long_every and (i + 1) % cfg.long_every == 0:
            n_suffix = cfg.long_prompt_tokens
        else:
            n_suffix = 1 + int(rng.geometric(1.0 / cfg.suffix_tokens_mean))
            if cfg.long_every and cfg.long_prompt_tokens:
                # keep the mixed workload bimodal: the geometric tail
                # must not wander into long-prompt territory
                n_suffix = min(n_suffix, cfg.long_prompt_tokens - 1)
        prompt = prefixes[tenant] + rng.integers(
            0, cfg.vocab, n_suffix).tolist()
        mid = (f"m{int(rng.choice(cfg.n_models, p=model_p))}"
               if model_p is not None else None)
        yield Request(t, tenant, prompt, max_new=cfg.max_new_tokens,
                      model_id=mid)


def gen_trace(cfg: TraceConfig) -> List[Request]:
    """Materialized trace (tests / small scales)."""
    return list(iter_trace(cfg))


# ---------------------------------------------------------------------------
# replay harness (bounded memory at any request count)
# ---------------------------------------------------------------------------

class _Reservoir:
    """Fixed-size uniform sample of a stream — percentile estimates for
    traces far too long to keep every latency (1M requests x 64 TPOTs
    would be half a GB as floats)."""

    def __init__(self, cap: int = 65536, seed: int = 0):
        import random

        self.cap = cap
        self.n = 0
        self.xs: List[float] = []
        self._rng = random.Random(seed)

    def add(self, x: float) -> None:
        self.n += 1
        if len(self.xs) < self.cap:
            self.xs.append(x)
        else:
            j = self._rng.randrange(self.n)
            if j < self.cap:
                self.xs[j] = x

    def percentile(self, q: float) -> float:
        from ray_tpu.serve.admission import _percentile

        return _percentile(sorted(self.xs), q)


@dataclass
class ReplayStats:
    started: int = 0
    completed: int = 0
    shed: int = 0
    deadline: int = 0
    errors: int = 0
    tokens: int = 0
    wall_s: float = 0.0
    ttft: _Reservoir = field(default_factory=_Reservoir)
    tpot: _Reservoir = field(default_factory=_Reservoir)

    def summary(self) -> Dict[str, Any]:
        return {
            "requests": self.started,
            "completed": self.completed,
            "shed": self.shed,
            "deadline_exceeded": self.deadline,
            "errors": self.errors,
            "tokens": self.tokens,
            "wall_s": round(self.wall_s, 3),
            "tokens_per_s": round(self.tokens / self.wall_s, 2)
            if self.wall_s else 0.0,
            "shed_rate": round(self.shed / max(self.started, 1), 4),
            "ttft_p50_s": round(self.ttft.percentile(0.50), 4),
            "ttft_p99_s": round(self.ttft.percentile(0.99), 4),
            "tpot_p50_s": round(self.tpot.percentile(0.50), 5),
            "tpot_p99_s": round(self.tpot.percentile(0.99), 5),
        }


def classify_error(e: BaseException) -> str:
    """"shed" / "deadline" / "error" off the machine-readable
    ``error_type`` that admission errors declare and ``TaskError``
    wrappers now carry across process boundaries (ISSUE 13 satellite —
    no more str()-prefix matching)."""
    from ray_tpu.serve.admission import (DeadlineExceededError,
                                         RequestShedError)

    seen = set()
    cur: Optional[BaseException] = e
    while cur is not None and id(cur) not in seen:
        seen.add(id(cur))
        if isinstance(cur, RequestShedError):
            return "shed"
        if isinstance(cur, DeadlineExceededError):
            return "deadline"
        et = getattr(cur, "error_type", None)
        if et in ("shed", "deadline"):
            return et
        cur = getattr(cur, "cause", None) or cur.__cause__
    return "error"


def replay(stream_fn: Callable[[Request], Iterable[int]],
           trace: Iterable[Request], *, time_scale: float = 1.0,
           max_clients: int = 32,
           on_error: Optional[Callable[[Request, BaseException], str]]
           = None, max_wall_s: Optional[float] = None,
           progress_every: int = 0) -> ReplayStats:
    """Drive the trace against ``stream_fn`` (request -> token iterator),
    honoring arrival times (``time_scale`` stretches/compresses them;
    0 = closed loop). Each in-flight request holds one client thread —
    the streaming consumption model real callers have — and at most
    ``max_clients`` are alive at once, so memory is bounded by the
    client window, never the trace length. ``on_error`` overrides the
    default ``classify_error``. ``max_wall_s`` stops ADMITTING new
    requests after the budget (already-started streams drain)."""
    stats = ReplayStats()
    lock = threading.Lock()
    sem = threading.Semaphore(max_clients)
    t0 = time.monotonic()
    classify = on_error or (lambda req, e: classify_error(e))

    def client(req: Request) -> None:
        try:
            t_submit = time.monotonic()
            first = None
            last = t_submit
            n = 0
            try:
                for tok in stream_fn(req):
                    now = time.monotonic()
                    if first is None:
                        first = now - t_submit
                    else:
                        with lock:
                            stats.tpot.add(now - last)
                    last = now
                    n += 1
            except BaseException as e:  # noqa: BLE001 - classified below
                kind = classify(req, e)
                with lock:
                    if kind == "shed":
                        stats.shed += 1
                    elif kind == "deadline":
                        stats.deadline += 1
                    else:
                        stats.errors += 1
                    stats.tokens += n
                return
            with lock:
                stats.completed += 1
                stats.tokens += n
                if first is not None:
                    stats.ttft.add(first)
        finally:
            sem.release()

    truncated = False
    for req in trace:
        if max_wall_s is not None \
                and time.monotonic() - t0 > max_wall_s:
            truncated = True
            break
        target = t0 + req.arrival_s * time_scale
        delay = target - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        sem.acquire()
        stats.started += 1
        threading.Thread(target=client, args=(req,), daemon=True).start()
        if progress_every and stats.started % progress_every == 0:
            print(f"# replay: {stats.started} started, "
                  f"{stats.completed} done, "
                  f"{time.monotonic() - t0:.0f}s", file=sys.stderr)
    # drain: re-acquire every client permit (each release marks one
    # client finished) — no per-thread bookkeeping, so a 1M-request
    # replay never holds 1M Thread objects
    deadline = time.monotonic() + 600
    for _ in range(max_clients):
        if not sem.acquire(timeout=max(0.1, deadline - time.monotonic())):
            break
    stats.wall_s = time.monotonic() - t0
    if truncated:
        stats.truncated = True  # type: ignore[attr-defined]
    return stats


# ---------------------------------------------------------------------------
# drivers: in-process engines (bench A/Bs) and deployed applications
# ---------------------------------------------------------------------------

class EngineRunner:
    """Minimal deployment-shaped wrapper over one in-process LLMEngine:
    a stepper thread plus a queue-backed token stream per request — the
    same-container A/B vehicle (no actor boot noise in the numbers)."""

    def __init__(self, engine):
        self.engine = engine
        self._stop = False
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while not self._stop:
            if not self.engine.step():
                time.sleep(0.001)

    def stream(self, req: Request,
               deadline_s: Optional[float] = None) -> Iterable[int]:
        import queue as _q

        q: "_q.Queue[Any]" = _q.Queue()
        r = self.engine.submit(req.prompt, req.max_new, q.put_nowait,
                               deadline_s=deadline_s)
        try:
            while True:
                tok = q.get(timeout=120.0)
                if tok is None:
                    return
                if isinstance(tok, BaseException):
                    raise tok
                yield tok
        finally:
            self.engine.cancel(r)

    def close(self):
        self._stop = True
        self._thread.join(timeout=5)


def run_engine_ab(scale: str = "quick", paged: bool = True,
                  prefix_cache: bool = True, seed: int = 0,
                  model: str = "llama-debug",
                  time_scale: float = 0.0) -> Dict[str, Any]:
    """One replay against one in-process engine; returns the scorecard
    plus engine KV/prefix state. ``time_scale=0`` = closed-loop (submit
    as fast as clients free up) — the throughput-capability measurement;
    > 0 replays real arrival times."""
    from ray_tpu.serve.llm import LLMEngine
    from ray_tpu.util.tpu_info import honor_jax_platform_env

    honor_jax_platform_env()
    cfg = _scale_trace(scale, seed)
    engine = LLMEngine(model, max_slots=8, max_len=256, seed=seed,
                       paged=paged, prefix_cache=prefix_cache,
                       block_size=16, prefill_chunk=8)
    runner = EngineRunner(engine)
    try:
        first = next(iter_trace(cfg))
        # warm the compile out of the measurement
        list(runner.stream(Request(0.0, 0, first.prompt[:8], 2)))
        stats = replay(runner.stream, iter_trace(cfg),
                       time_scale=time_scale)
    finally:
        runner.close()
    out = stats.summary()
    kv = engine.kv_state()
    if "prefix" in kv:
        p = kv["prefix"]
        lookups = max(p["hits"] + p["misses"], 1)
        out["prefix_hit_rate"] = round(p["hits"] / lookups, 4)
        out["prefix_hit_tokens"] = p["hit_tokens"]
    out["paged"] = paged
    return out


def run_disagg_ab(scale: str = "quick", *, disagg: bool,
                  seed: int = 0,
                  model: str = "llama-debug") -> Dict[str, Any]:
    """Colocated-vs-disaggregated same-container A/B (ISSUE 13): TWO
    engines either way — colocated mode routes whole requests to the
    less-loaded engine; disagg mode dedicates one to chunked prefill
    and one to decode, shipping KV blocks over the real DeviceChannel
    path between them. Same hardware, same trace (mixed: steady short
    prompts + periodic long prompts), so the delta IS the architecture:
    long prefills stop sharing a step with in-flight decodes."""
    from ray_tpu.serve.llm import LLMDeployment
    from ray_tpu.util.tpu_info import honor_jax_platform_env

    honor_jax_platform_env()
    cfg = _mixed_cfg(_scale_trace(scale, seed))
    kw = dict(_MIXED_ENGINE_KW, seed=seed)
    kw["max_len"] = _mixed_max_len(cfg, kw["block_size"])
    if disagg:
        # same TOTAL KV memory as the colocated pair (2x the per-engine
        # default), split by role: prefill holds only the transient
        # working set of in-flight prompts, decode keeps the sessions +
        # prefix cache — a decode pool sized like a colocated engine
        # would run at permanent pool pressure (every adopt evicts)
        base_blocks = kw["max_slots"] * (kw["max_len"]
                                         // kw["block_size"])
        pools = [LLMDeployment(model, role="prefill",
                               num_blocks=3 * base_blocks // 4, **kw),
                 # decode never prefills: one-block prefill_chunk keeps
                 # the chunk's dead compute out of every decode step
                 LLMDeployment(model, role="decode",
                               num_blocks=5 * base_blocks // 4,
                               **dict(kw,
                                      prefill_chunk=kw["block_size"]))]
        node = pools[0].identity()["node"]

        def stream(req: Request) -> Iterable[int]:
            rid = uuid.uuid4().hex
            desc = pools[0].prefill_export(
                req.prompt, {"req": rid, "dst": "decode0",
                             "dst_node": node})
            return pools[1].adopt_stream(req.prompt, desc, req.max_new)
    else:
        kw = dict(kw, prefill_chunk=_MIXED_COLOC_CHUNK)
        pools = [LLMDeployment(model, role="colocated", **kw),
                 LLMDeployment(model, role="colocated", **kw)]

        def stream(req: Request) -> Iterable[int]:
            states = [p.engine.kv_state() for p in pools]
            loads = [s["inflight"] + s["queued"] for s in states]
            return pools[loads.index(min(loads))](
                req.prompt, req.max_new)

    try:
        first = next(iter_trace(cfg))
        # warm every engine's compile paths out of the measurement
        for p in _mixed_warm_prompts(cfg, first.prompt * 16,
                                     kw["block_size"]):
            for _ in range(2):
                list(stream(Request(0.0, 0, list(p), 2)))
        stats = replay(stream, iter_trace(cfg), time_scale=0.0,
                       max_clients=8)
    finally:
        for p in pools:
            p.close()   # in-process: nosess rings have no sweep
    out = stats.summary()
    out["mode"] = "disagg" if disagg else "colocated"
    states = [p.engine.kv_state() for p in pools]
    out["kv_leaks"] = sum(
        s["kv_total"] - s["kv_free"] - s["prefix"]["nodes"]
        for s in states)
    out["exported"] = sum(p.engine.stats["exported"] for p in pools)
    out["adopted"] = sum(p.engine.stats["adopted"] for p in pools)
    return out


def run_multiplex_ab(scale: str = "quick", *, dedicated: bool,
                     n_models: int = 8, replicas: int = 2,
                     speculative: bool = False,
                     budget_models: int = 2, seed: int = 0,
                     model: str = "llama-debug") -> Dict[str, Any]:
    """Multi-model consolidation A/B (ISSUE 16): the SAME Zipf trace
    over ``n_models`` models, the SAME fleet-wide weight budget of
    ``replicas * budget_models`` resident model-slots, two ways of
    spending it. The DEDICATED arm does what static allocation does:
    deploys the Zipf-hottest models that fit the budget, one engine
    each, and hard-sheds every request for a model it chose not to
    host. The MULTIPLEX arm serves ALL ``n_models`` through
    ``replicas`` multiplexed deployments whose registries page weights
    in and out of the same per-replica budget on demand (LRU under
    in-flight pinning) — the swap counters in the output are the proof
    that the tail models were PAGED, not resident. Replay is
    open-loop at ~75% of fleet capacity, so a shed request is lost
    tokens at unchanged wall time, exactly what it is in production.

    Routing in the multiplex arm is sticky-home (models greedy-packed
    onto replicas by Zipf weight — steady traffic partitions the fleet
    into full batches exactly like dedicated deployments would) with
    budget-shed retries walking the other replicas and then waiting
    for an in-flight pin to drain; eager least-inflight splitting
    would fragment the hot model's batches on every request.

    ``budget_models=0`` removes the budget from BOTH arms (dedicated
    hosts all ``n_models``; the registry pages lazily but never
    evicts) — the capacity-unconstrained control."""
    from ray_tpu.serve.admission import RequestShedError
    from ray_tpu.serve.llm import LLMDeployment
    from ray_tpu.serve.multiplex import MultiplexedLLMDeployment
    from ray_tpu.util.tpu_info import honor_jax_platform_env

    honor_jax_platform_env()
    cfg = _scale_trace(scale, seed)
    cfg.n_models = n_models
    cfg.zipf_alpha = 1.0
    cfg.max_new_tokens = max(cfg.max_new_tokens, 32)
    # steady open-loop arrivals at ~75% of measured fleet capacity
    # (~700 tok/s on the 2-vCPU CI box): wall time is set by the
    # ARRIVAL span, so the dedicated arm cannot convert its sheds into
    # a shorter run — lost requests are lost tokens
    cfg.n_requests = max(cfg.n_requests, 96)
    cfg.burst_rps = 16.0
    cfg.burst_len_s = 1e9        # steady Poisson, no off-gaps
    model_ids = [f"m{i}" for i in range(n_models)]
    fleet_slots = (replicas * budget_models if budget_models > 0
                   else n_models)
    kw = dict(max_slots=8, max_len=256, block_size=16, prefill_chunk=8)
    lock = threading.Lock()
    if dedicated:
        # static allocation: one single-model deployment per hosted
        # model, Zipf-hottest first, as many as the weight budget
        # seats; per-model seeds match the multiplex arm's registry
        # (identical weights per arm)
        deps = {mid: LLMDeployment(model, seed=seed + i, **kw)
                for i, mid in enumerate(model_ids[:fleet_slots])}
        pools: List[Any] = list(deps.values())

        def stream(req: Request) -> Iterable[int]:
            dep = deps.get(req.model_id)
            if dep is None:
                raise RequestShedError(
                    f"no deployment hosts {req.model_id!r} (fleet "
                    f"weight budget seats {fleet_slots} models)",
                    reason="model_budget")
            return dep(req.prompt, req.max_new)

        warm = [(dep, {}) for dep in deps.values()]
    else:
        spec = {mid: {"config": model, "seed": seed + i}
                for i, mid in enumerate(model_ids)}
        budget = None
        if budget_models > 0:
            import jax

            from ray_tpu import models as M

            c = M.get_config(model)
            one = M.params_bytes(M.init_params(jax.random.PRNGKey(0), c))
            budget = budget_models * one + 1
        pools = [MultiplexedLLMDeployment(
                     spec, budget_bytes=budget, speculative=speculative,
                     spec_accept_floor=0.0 if speculative else None,
                     seed=seed, **kw)
                 for _ in range(replicas)]
        w = [1.0 / (r + 1) ** cfg.zipf_alpha for r in range(n_models)]
        packed = [0.0] * replicas
        home: Dict[str, int] = {}
        for i, mid in enumerate(model_ids):
            j = packed.index(min(packed))
            home[mid] = j
            packed[j] += w[i]
        counts = [0] * replicas

        def _try(pick: int, req: Request):
            return pools[pick](req.prompt, req.max_new,
                               model_id=req.model_id)

        def stream(req: Request) -> Iterable[int]:
            # home first; on a model_budget shed walk the other
            # replicas; when every registry is pinned full, wait for a
            # stream to drain a pin and retry — the request queues for
            # a model-slot instead of dying
            deadline = time.monotonic() + 30.0
            while True:
                order = [home[req.model_id]] + [
                    j for j in range(replicas)
                    if j != home[req.model_id]]
                shed: Optional[BaseException] = None
                for pick in order:
                    try:
                        inner = _try(pick, req)
                        break
                    except RequestShedError as e:
                        if getattr(e, "reason", "") != "model_budget":
                            raise
                        shed = e
                else:
                    if time.monotonic() > deadline:
                        raise shed
                    time.sleep(0.025)
                    continue
                break
            with lock:
                counts[pick] += 1

            def gen() -> Iterator[int]:
                try:
                    yield from inner
                finally:
                    with lock:
                        counts[pick] -= 1

            return gen()

        warm = [(rep, {"model_id": mid})
                for rep in pools for mid in model_ids]
    try:
        first = next(iter_trace(cfg))
        # warm every (replica, model) engine's compile out of the
        # measurement — in the multiplex arm this IS the lazy
        # materialization (the registry counts the page-ins), and
        # under the budget it already runs the LRU churn the swap
        # counters report; a mid-run compile would stall every
        # in-flight decode on that replica
        for target, target_kw in warm:
            list(target(first.prompt[:8], 2, **target_kw))
            list(target(list(first.prompt), 2, **target_kw))
        stats = replay(stream, iter_trace(cfg), time_scale=1.0,
                       max_clients=32)
        # collect BEFORE close(): close tears down the lazy engines
        # and frees the registry entries the counters live on
        rep_stats = ([] if dedicated
                     else [rep.stats() for rep in pools])
    finally:
        for p in pools:
            p.close()
    out = stats.summary()
    out["mode"] = "dedicated" if dedicated else "multiplex"
    out["n_models"] = n_models
    out["fleet_model_slots"] = fleet_slots
    if dedicated:
        out["engines"] = len(pools)
        out["hosted_models"] = len(pools)
    else:
        snaps = [s["models"] for s in rep_stats]
        out["replicas"] = replicas
        out["engines"] = sum(len(s) - 1 for s in rep_stats)
        out["swaps_in"] = sum(r["swaps_in"] for s in snaps
                              for r in s.values())
        out["swaps_out"] = sum(r["swaps_out"] for s in snaps
                               for r in s.values())
        if budget_models > 0:
            out["budget_models"] = budget_models
        if speculative:
            agg = {"spec_proposed": 0, "spec_accepted": 0,
                   "spec_fallbacks": 0}
            for s in rep_stats:
                for mid, es in s.items():
                    if mid == "models":
                        continue
                    for k in agg:
                        agg[k] += es.get(k, 0)
            out.update(agg)
            out["speculative"] = True
    return out


def run_spec_ab(scale: str = "quick", *, spec: bool, seed: int = 0,
                model: str = "gpt2-debug",
                spec_k: int = 4) -> Dict[str, Any]:
    """Speculative-vs-plain same-engine A/B (ISSUE 16): one in-process
    engine, greedy decoding, same trace — the only difference is the
    drafter proposing ``spec_k`` tokens per step for one batched
    verify. Greedy spec is token-exact by construction (the parity
    tests assert it), so the delta here is pure tokens/s. The ngram
    drafter feeds on self-repetition, so acceptance (reported) is
    model- and trace-dependent; ``spec_accept_floor=0`` keeps the
    fallback out of the measurement."""
    from ray_tpu.serve.llm import LLMEngine
    from ray_tpu.serve.multiplex import SpeculativeLLMEngine
    from ray_tpu.util.tpu_info import honor_jax_platform_env

    honor_jax_platform_env()
    cfg = _scale_trace(scale, seed)
    # speculative decoding is a DECODE-phase lever: the drafter feeds
    # on the sequence's own repetition, which a handful of decode steps
    # never develops. Long-decode sessions are the workload it exists
    # for — size the trace accordingly (TTFT is untouched either way).
    cfg.max_new_tokens = max(cfg.max_new_tokens, 64)
    kw = dict(max_slots=8, max_len=256, seed=seed, paged=True,
              block_size=16, prefill_chunk=8)
    if spec:
        engine = SpeculativeLLMEngine(model, spec_k=spec_k,
                                      spec_accept_floor=0.0, **kw)
    else:
        engine = LLMEngine(model, **kw)
    runner = EngineRunner(engine)
    try:
        first = next(iter_trace(cfg))
        list(runner.stream(Request(0.0, 0, first.prompt[:8], 2)))
        list(runner.stream(Request(0.0, 0, list(first.prompt), 2)))
        stats = replay(runner.stream, iter_trace(cfg), time_scale=0.0,
                       max_clients=8)
    finally:
        runner.close()
    out = stats.summary()
    out["mode"] = "speculative" if spec else "plain"
    out["model"] = model
    if spec:
        s = engine.stats
        out["spec_k"] = spec_k
        out["spec_proposed"] = s.get("spec_proposed", 0)
        out["spec_accepted"] = s.get("spec_accepted", 0)
        out["spec_fallbacks"] = s.get("spec_fallbacks", 0)
        out["spec_accept_rate"] = round(
            s.get("spec_accepted", 0) / max(s.get("spec_proposed", 0),
                                            1), 4)
    return out


def run_affinity_ab(scale: str = "quick", *, replicas: int = 3,
                    seed: int = 0,
                    model: str = "llama-debug") -> Dict[str, Any]:
    """Cluster-wide prefix-affinity A/B (ISSUE 16): the same
    shared-prefix trace replayed three ways — ONE replica (the hit-rate
    ceiling: every tenant's prefix lives in the only trie), ``replicas``
    replicas routed by published prefix digests (the handle's affinity
    logic, mirrored in-process off each replica's ``load_state``), and
    ``replicas`` replicas routed at random (the scatter baseline that
    re-prefills every system prompt once per replica it lands on). The
    acceptance bar: affinity's hit rate within 0.05 of the
    single-replica ceiling."""
    import random as _random

    from ray_tpu.serve.kv_cache import prefix_key_digest
    from ray_tpu.serve.llm import LLMDeployment
    from ray_tpu.util.tpu_info import honor_jax_platform_env

    honor_jax_platform_env()
    kw = dict(max_slots=4, max_len=256, block_size=16, prefill_chunk=8,
              seed=seed)
    rng = _random.Random(seed)

    def one_replay(mode: str) -> Dict[str, Any]:
        n = 1 if mode == "single" else replicas
        pools = [LLMDeployment(model, **kw) for _ in range(n)]
        lock = threading.Lock()
        counts = [0] * n
        digests: Dict[int, Dict[str, int]] = {}
        ts = [0.0]

        def _pick(req: Request) -> int:
            if n == 1:
                return 0
            if mode == "scatter":
                return rng.randrange(n)
            with lock:
                now = time.monotonic()
                if now - ts[0] > 0.05:
                    ts[0] = now
                    for j, p in enumerate(pools):
                        digests[j] = dict(
                            p.load_state().get("prefix_digest", []))
                key = prefix_key_digest(
                    list(req.prompt)[:kw["block_size"]])
                best, best_w = None, -1
                for j in range(n):
                    w = digests.get(j, {}).get(key)
                    if w is not None and int(w) > best_w:
                        best, best_w = j, int(w)
                if best is None:
                    # cold prefix — no replica has published it yet.
                    # Rendezvous-hash the key so every request of the
                    # tenant lands on the SAME replica before its
                    # digest exists; least-counts here scatters the
                    # opening burst across the fleet, planting the
                    # prefix in every trie it touches and paying the
                    # re-prefill once per replica.
                    best = max(range(n),
                               key=lambda j: _hash.sha1(
                                   f"{key}:{j}".encode()).digest())
                counts[best] += 1
                return best

        def stream(req: Request) -> Iterable[int]:
            pick = _pick(req)
            inner = pools[pick](req.prompt, req.max_new)

            def gen() -> Iterator[int]:
                try:
                    yield from inner
                finally:
                    if mode == "affinity":
                        with lock:
                            counts[pick] -= 1

            return gen()

        cfg = _scale_trace(scale, seed)
        try:
            first = next(iter_trace(cfg))
            for p in pools:
                list(p(first.prompt[:8], 2))
                list(p(list(first.prompt), 2))
            # baseline the trie counters after warm-up: the warm pass
            # runs PER REPLICA, so without the subtraction the
            # multi-replica arms are charged n-1 extra sets of warm
            # misses the single-replica ceiling never pays
            base = []
            for p in pools:
                pf = p.engine.kv_state().get("prefix", {})
                base.append((pf.get("hits", 0), pf.get("misses", 0)))
            stats = replay(stream, iter_trace(cfg), time_scale=0.0,
                           max_clients=4)
            hits = lookups = 0
            for p, (bh, bm) in zip(pools, base):
                pf = p.engine.kv_state().get("prefix", {})
                h = pf.get("hits", 0) - bh
                m = pf.get("misses", 0) - bm
                hits += h
                lookups += h + m
        finally:
            for p in pools:
                p.close()
        out = stats.summary()
        out["hit_rate"] = round(hits / max(lookups, 1), 4)
        return out

    arms = {m: one_replay(m) for m in ("single", "affinity", "scatter")}
    return {
        "mode": "affinity_ab",
        "replicas": replicas,
        "single_hit_rate": arms["single"]["hit_rate"],
        "affinity_hit_rate": arms["affinity"]["hit_rate"],
        "scatter_hit_rate": arms["scatter"]["hit_rate"],
        "affinity_within": round(arms["single"]["hit_rate"]
                                 - arms["affinity"]["hit_rate"], 4),
        "affinity_ok": (arms["single"]["hit_rate"]
                        - arms["affinity"]["hit_rate"]) <= 0.05,
        "arms": arms,
    }


#: engine shape for the mixed-workload A/Bs. prefill_chunk is the
#: colocated dilemma knob — one setting must serve prefill throughput
#: AND decode cadence. The colocated arm runs its measured-best
#: compromise (chunk 16: on CPU a chunk step costs ~linearly in chunk
#: width, so narrow chunks barely tax prefill; the swept 16/32/64/128
#: settings go 229/184/113/61 tok/s); the disagg arms dissolve the
#: dilemma per pool — prefill replicas take the wide chunk below,
#: decode replicas shrink it to one block (the compiled step carries
#: the chunk's compute whether or not anything is prefilling).
_MIXED_ENGINE_KW = dict(max_slots=8, max_len=512, block_size=16,
                        prefill_chunk=128)
_MIXED_COLOC_CHUNK = 16


def _mixed_cfg(cfg: TraceConfig) -> TraceConfig:
    """Salt a trace with the disaggregation workload: steady sessions
    emitting tokens while every 4th arrival carries a LONG prompt — the
    pattern where colocated prefill steals decode step-time, and enough
    prefill work on the wire that a dedicated prefill pool pulls its
    weight against the all-mixed baseline."""
    cfg.max_new_tokens = max(cfg.max_new_tokens, 96)
    cfg.long_every = 4
    cfg.long_prompt_tokens = 352
    return cfg


def _mixed_warm_prompts(cfg: TraceConfig, base: List[int],
                        block_size: int) -> List[List[int]]:
    """Warm prompts covering the gather/scatter jit BUCKETS real
    mixed-trace prompts hit (pow2 block counts: short mixed prompts
    land in the 4- and 8-block buckets, long ones at the top) — a
    mid-run compile would stall every in-flight decode and poison
    exactly the tail the A/Bs measure. ONE definition for every
    harness: the bucket set encodes the engine's jit-bucket contract."""
    return [base[:16], base[:4 * block_size], base[:7 * block_size],
            base[:cfg.shared_prefix_tokens + cfg.long_prompt_tokens],
            base[:16]]


def _mixed_max_len(cfg: TraceConfig, block_size: int) -> int:
    """Engine max_len that FITS the mixed trace's worst request
    (prefix + long prompt + decode budget, block-rounded): the quick
    scale fits the default 512, but medium/full prefixes (96/128) push
    the worst case past it — an undersized engine turns every long
    request into a submit-time ValueError and poisons the A/B."""
    need = (cfg.shared_prefix_tokens + cfg.long_prompt_tokens
            + cfg.max_new_tokens)
    need = ((need + block_size - 1) // block_size) * block_size
    return max(_MIXED_ENGINE_KW["max_len"], need)


def _scale_trace(scale: str, seed: int) -> TraceConfig:
    if scale == "quick":          # 2-vCPU CI tier
        return TraceConfig(n_requests=48, n_tenants=3,
                           shared_prefix_tokens=48, max_new_tokens=8,
                           burst_rps=200.0, seed=seed)
    if scale == "medium":
        return TraceConfig(n_requests=2_000, n_tenants=8,
                           shared_prefix_tokens=96, max_new_tokens=32,
                           burst_rps=500.0, seed=seed)
    # full: the millions-of-requests envelope (real hardware only)
    return TraceConfig(n_requests=1_000_000, n_tenants=64,
                       shared_prefix_tokens=128, max_new_tokens=64,
                       burst_rps=2_000.0, seed=seed)


def _boot_cluster(nodes: int):
    """Extra node daemons for --nodes (multi-node replay): returns the
    Cluster handle (caller shuts down) after registering ``nodes`` extra
    daemons beside the head."""
    from ray_tpu.cluster import Cluster

    c = Cluster()
    for _ in range(nodes):
        c.add_node(num_cpus=2)
    return c


def run_serve_replay(scale: str, replicas: int, paged: bool,
                     seed: int = 0, deadline_s: Optional[float] = None,
                     slo: Optional[dict] = None, nodes: int = 0,
                     disagg: bool = False,
                     slo_ttft_s: Optional[float] = None,
                     max_wall_s: Optional[float] = None,
                     mixed: bool = False,
                     max_new: Optional[int] = None,
                     max_clients: int = 32) -> Dict[str, Any]:
    """Deploy a multi-replica application and replay through the real
    routing path (load-aware picker, admission, streaming). ``disagg``
    splits the replicas into a prefill pool and a decode pool and
    routes through the transfer-aware DisaggHandle; ``nodes`` boots
    that many extra node daemons first (multi-node envelope); ``mixed``
    salts the trace with periodic long prompts (the disaggregation A/B
    workload); ``max_new`` overrides the trace's per-request decode
    length (the envelope knob that fits a 1M-request run onto a
    CPU-only box — TTFT, the declared SLO, is decode-length
    independent). The output carries an SLO verdict (p99 TTFT vs
    ``slo_ttft_s``) and a zero-leak KV audit across every replica of
    every pool."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve import LLMDeployment

    cluster = None
    if nodes > 0:
        cluster = _boot_cluster(nodes)
        ray_tpu.init(address=cluster.address,
                     cluster_authkey=cluster.authkey, num_cpus=2)
    else:
        ray_tpu.init(ignore_reinit_error=True)
    if disagg:
        paged = True   # KV export/adopt is block-granular by definition
    if mixed:
        engine_kw = dict(_MIXED_ENGINE_KW, seed=seed)
        mixed_cfg = _mixed_cfg(_scale_trace(scale, seed))
        if max_new is not None:    # the override lands on the trace
            mixed_cfg.max_new_tokens = max_new  # — size for it too
        engine_kw["max_len"] = _mixed_max_len(
            mixed_cfg, engine_kw["block_size"])
        if not disagg:
            engine_kw["prefill_chunk"] = _MIXED_COLOC_CHUNK
    else:
        engine_kw = dict(max_slots=8, max_len=256, seed=seed,
                         block_size=16, prefill_chunk=8)
    try:
        if disagg:
            # same TOTAL KV memory as a colocated deployment of the
            # same replica count, split by role (see run_disagg_ab)
            base_blocks = engine_kw["max_slots"] * (
                engine_kw["max_len"] // engine_kw["block_size"])
            prefill_kw = {"num_blocks": 3 * base_blocks // 4}
            # the decode pool never prefills, but the compiled step
            # carries the prefill_chunk-wide prefill slice either
            # way — shrink it to one block so decode-only steps
            # stop paying the chunk's dead compute
            decode_kw = {"num_blocks": 5 * base_blocks // 4,
                         "prefill_chunk": engine_kw["block_size"]}
            if scale == "full":
                # the 1M envelope: per-request work is dominated by
                # per-STEP and per-MESSAGE overhead, not FLOPs.
                # prefill pool: 64 tenants x 8 prefix blocks = 512
                # blocks of trie + the in-flight working set — an
                # undersized pool thrashes the trie and every prompt
                # re-prefills its 128-token system prompt (measured:
                # hit rate 0.42 -> 0.97, and prefill-step time is THE
                # full-scale bottleneck). decode pool: adoption always
                # claims fresh blocks, so a decode-side trie is pure
                # eviction overhead — disable it. stream_batch turns
                # lagging consumers' N token messages into 1 (TTFT —
                # the declared SLO — is untouched).
                engine_kw["prefill_chunk"] = 32
                engine_kw["stream_batch"] = 8
                prefill_kw["num_blocks"] = 5 * base_blocks
                decode_kw.update(num_blocks=3 * base_blocks,
                                 max_slots=16, prefix_cache=False)
            handle = serve.deploy_disagg(
                "llama-debug", name="llm_replay",
                prefill_replicas=max(1, replicas // 2),
                decode_replicas=max(1, replicas - replicas // 2),
                slo=slo,
                prefill_engine_kwargs=prefill_kw,
                decode_engine_kwargs=decode_kw,
                **engine_kw)

            def stream(req: Request):
                return handle.stream(req.prompt, req.max_new,
                                     deadline_s=deadline_s)

            warm_stream = stream
        else:
            app = serve.deployment(
                LLMDeployment, num_replicas=replicas,
                ray_actor_options={"max_concurrency": 16, "num_cpus": 0},
            ).bind("llama-debug", paged=paged, slo=slo, **engine_kw)
            sh = serve.run(app, name="llm_replay").options(stream=True)

            def stream(req: Request):
                for tok in sh.remote(req.prompt, req.max_new,
                                     deadline_s=deadline_s):
                    # stream_batch replicas deliver token chunks (lists)
                    if isinstance(tok, list):
                        yield from tok
                    else:
                        yield tok

            warm_stream = stream

        trace_cfg = _scale_trace(scale, seed)
        if mixed:
            trace_cfg = _mixed_cfg(trace_cfg)
        if max_new is not None:
            trace_cfg.max_new_tokens = max_new
        first = next(iter_trace(trace_cfg))
        warm_prompts = [first.prompt[:8], list(first.prompt)]
        if mixed:
            warm_prompts += _mixed_warm_prompts(
                trace_cfg, first.prompt * 16, engine_kw["block_size"])
        for wp in warm_prompts:
            for _ in range(replicas * 2):  # warm every replica's compile
                list(warm_stream(Request(0.0, 0, list(wp), 2)))
        stats = replay(stream, iter_trace(trace_cfg), time_scale=0.0,
                       max_wall_s=max_wall_s, max_clients=max_clients,
                       progress_every=10_000 if scale != "quick" else 0)
        out = stats.summary()
        out["replicas"] = replicas
        out["paged"] = paged
        out["disagg"] = disagg
        out["nodes"] = 1 + nodes
        if engine_kw.get("stream_batch", 1) > 1:
            out["stream_batch"] = engine_kw["stream_batch"]
        if getattr(stats, "truncated", False):
            out["truncated"] = True

        # per-pool KV/prefix state + ZERO-LEAK audit, enumerating the
        # replicas directly (a ROUTED probe can land on one replica
        # twice and double-count its hits)
        if disagg:
            states = handle.kv_states()
        else:
            h = serve.get_deployment_handle("LLMDeployment")
            h._refresh(force=True)
            states = {"colocated": [
                ray_tpu.get(r.handle_request.remote("kv_state", (), {}),
                            timeout=60) for r in h._replicas]}
        hits = lookups = leaks = 0
        for pool in states.values():
            for s in pool:
                hits += s.get("prefix", {}).get("hits", 0)
                lookups += (s.get("prefix", {}).get("hits", 0)
                            + s.get("prefix", {}).get("misses", 0))
                # dense engines have no block pool: nothing to audit
                leaks += (s.get("kv_total", 0) - s.get("kv_free", 0)
                          - s.get("prefix", {}).get("nodes", 0))
        out["prefix_hit_rate"] = round(hits / max(lookups, 1), 4)
        out["kv_leaks"] = leaks
        if slo_ttft_s is not None:
            out["slo"] = {
                "declared_ttft_p99_s": slo_ttft_s,
                "measured_ttft_p99_s": out["ttft_p99_s"],
                "ok": out["ttft_p99_s"] <= slo_ttft_s,
            }
        if disagg:
            handle.shutdown()
        else:
            serve.delete("LLMDeployment")
        return out
    finally:
        try:
            serve.shutdown()
            ray_tpu.shutdown()
        except Exception:
            pass
        if cluster is not None:
            cluster.shutdown()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--scale", default="quick",
                   choices=("quick", "medium", "full"))
    p.add_argument("--serve", action="store_true",
                   help="drive a deployed multi-replica app (default: "
                        "in-process engine A/B)")
    p.add_argument("--replicas", type=int, default=2)
    p.add_argument("--dense", action="store_true",
                   help="dense baseline instead of paged")
    p.add_argument("--disagg", action="store_true",
                   help="disaggregated prefill/decode pools (with "
                        "--serve: deployed pools; alone: in-process "
                        "two-engine A/B)")
    p.add_argument("--colocated", action="store_true",
                   help="with --disagg (in-process): run the colocated "
                        "baseline arm instead")
    p.add_argument("--multi-model", action="store_true",
                   help="multi-model Zipf trace through multiplexed "
                        "replicas (in-process A/B; ISSUE 16)")
    p.add_argument("--dedicated", action="store_true",
                   help="with --multi-model: run the N dedicated "
                        "single-model deployments baseline arm instead")
    p.add_argument("--n-models", type=int, default=8,
                   help="distinct models in the multi-model trace")
    p.add_argument("--budget-models", type=int, default=2,
                   help="with --multi-model: resident model-slots per "
                        "replica — the fleet weight budget BOTH arms "
                        "spend (0 = unbounded)")
    p.add_argument("--spec", action="store_true",
                   help="speculative-decoding engine A/B (in-process; "
                        "ISSUE 16); with --multi-model: speculative "
                        "multiplexed replicas")
    p.add_argument("--plain", action="store_true",
                   help="with --spec: run the plain-decoding baseline "
                        "arm instead")
    p.add_argument("--affinity", action="store_true",
                   help="prefix-affinity routing A/B over --replicas "
                        "replicas (in-process; ISSUE 16)")
    p.add_argument("--nodes", type=int, default=0,
                   help="extra node daemons to boot (multi-node replay)")
    p.add_argument("--slo-ttft-s", type=float, default=None,
                   help="declared p99 TTFT SLO; the output carries the "
                        "verdict")
    p.add_argument("--max-wall-s", type=float, default=None,
                   help="stop admitting new requests after this budget")
    p.add_argument("--mixed", action="store_true",
                   help="salt the trace with periodic long prompts "
                        "(the disaggregation A/B workload)")
    p.add_argument("--max-new", type=int, default=None,
                   help="override per-request decode length (the "
                        "envelope knob for CPU-only full-scale runs)")
    p.add_argument("--max-clients", type=int, default=32,
                   help="max concurrently in-flight requests")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)
    if args.serve:
        out = run_serve_replay(args.scale, args.replicas,
                               paged=not args.dense, seed=args.seed,
                               nodes=args.nodes, disagg=args.disagg,
                               slo_ttft_s=args.slo_ttft_s,
                               max_wall_s=args.max_wall_s,
                               mixed=args.mixed, max_new=args.max_new,
                               max_clients=args.max_clients)
    elif args.multi_model:
        out = run_multiplex_ab(args.scale, dedicated=args.dedicated,
                               n_models=args.n_models,
                               replicas=args.replicas,
                               speculative=args.spec,
                               budget_models=args.budget_models,
                               seed=args.seed)
    elif args.spec:
        out = run_spec_ab(args.scale, spec=not args.plain,
                          seed=args.seed)
    elif args.affinity:
        out = run_affinity_ab(args.scale, replicas=args.replicas,
                              seed=args.seed)
    elif args.disagg:
        out = run_disagg_ab(args.scale, disagg=not args.colocated,
                            seed=args.seed)
    else:
        out = run_engine_ab(args.scale, paged=not args.dense,
                            seed=args.seed)
    print(json.dumps({"metric": "serve_replay", **out}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
