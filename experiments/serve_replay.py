"""Traffic-replay load generator for the LLM serving tier (ISSUE 12).

Replays a synthetic multi-tenant trace — a shared-prefix mixture (each
tenant has a fixed system prompt; its requests append distinct user
suffixes) with bursty on/off arrivals — against either an in-process
:class:`~ray_tpu.serve.llm.LLMEngine` (the same-container A/B mode
``bench.py``'s ``serve_llm`` section uses) or a deployed multi-replica
application (``python experiments/serve_replay.py --serve``), and
reports the serving-tier scorecard:

    tokens/s (generated), TTFT p50/p99, TPOT p50/p99,
    prefix-cache hit rate, shed rate, error count

Scale-parameterized: ``--scale quick`` fits the 2-vCPU CI tier
(hundreds of requests, tiny model); ``--scale full`` targets the
ROADMAP's millions-of-requests envelope on real hardware (the trace
generator is O(1) memory per in-flight request, so the envelope is
bounded by the cluster, not the harness).

Prints ONE JSON line (the bench.py contract).
"""

from __future__ import annotations

import argparse
import json

import os
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:  # runnable as `python experiments/serve_replay.py`
    sys.path.insert(0, _REPO)


# ---------------------------------------------------------------------------
# trace generation
# ---------------------------------------------------------------------------

@dataclass
class TraceConfig:
    n_requests: int = 200
    n_tenants: int = 4
    shared_prefix_tokens: int = 48     # per-tenant system prompt length
    suffix_tokens_mean: int = 12       # user-suffix length (geometric-ish)
    max_new_tokens: int = 16
    vocab: int = 256
    # bursty arrivals: ON periods at burst_rps, OFF gaps between bursts
    burst_rps: float = 50.0
    burst_len_s: float = 0.5
    gap_s: float = 0.25
    seed: int = 0


@dataclass
class Request:
    arrival_s: float
    tenant: int
    prompt: List[int]
    max_new: int


def gen_trace(cfg: TraceConfig) -> List[Request]:
    """Deterministic multi-tenant trace: tenant system prompts are fixed
    per seed; arrivals are an on/off burst process (the shape that
    separates load-aware routing from round-robin — bursts pile onto
    whichever replica round-robin happens to hit mid-burst)."""
    import numpy as np

    rng = np.random.default_rng(cfg.seed)
    prefixes = [rng.integers(0, cfg.vocab, cfg.shared_prefix_tokens)
                .tolist() for _ in range(cfg.n_tenants)]
    out: List[Request] = []
    t = 0.0
    in_burst_left = cfg.burst_len_s
    for _ in range(cfg.n_requests):
        # exponential inter-arrival inside a burst; jump the gap when the
        # burst budget is spent
        dt = float(rng.exponential(1.0 / cfg.burst_rps))
        in_burst_left -= dt
        if in_burst_left <= 0:
            t += cfg.gap_s
            in_burst_left = cfg.burst_len_s
        t += dt
        tenant = int(rng.integers(cfg.n_tenants))
        n_suffix = 1 + int(rng.geometric(1.0 / cfg.suffix_tokens_mean))
        prompt = prefixes[tenant] + rng.integers(
            0, cfg.vocab, n_suffix).tolist()
        out.append(Request(t, tenant, prompt,
                           max_new=cfg.max_new_tokens))
    return out


# ---------------------------------------------------------------------------
# replay harness
# ---------------------------------------------------------------------------

@dataclass
class ReplayStats:
    started: int = 0
    completed: int = 0
    shed: int = 0
    deadline: int = 0
    errors: int = 0
    tokens: int = 0
    wall_s: float = 0.0
    ttft: List[float] = field(default_factory=list)
    tpot: List[float] = field(default_factory=list)

    def _pct(self, xs: List[float], q: float) -> float:
        from ray_tpu.serve.admission import _percentile

        return _percentile(sorted(xs), q)

    def summary(self) -> Dict[str, Any]:
        return {
            "requests": self.started,
            "completed": self.completed,
            "shed": self.shed,
            "deadline_exceeded": self.deadline,
            "errors": self.errors,
            "tokens": self.tokens,
            "wall_s": round(self.wall_s, 3),
            "tokens_per_s": round(self.tokens / self.wall_s, 2)
            if self.wall_s else 0.0,
            "shed_rate": round(self.shed / max(self.started, 1), 4),
            "ttft_p50_s": round(self._pct(self.ttft, 0.50), 4),
            "ttft_p99_s": round(self._pct(self.ttft, 0.99), 4),
            "tpot_p50_s": round(self._pct(self.tpot, 0.50), 5),
            "tpot_p99_s": round(self._pct(self.tpot, 0.99), 5),
        }


def replay(stream_fn: Callable[[Request], Iterable[int]],
           trace: List[Request], *, time_scale: float = 1.0,
           max_clients: int = 32,
           on_error: Optional[Callable[[Request, BaseException], str]]
           = None) -> ReplayStats:
    """Drive the trace against ``stream_fn`` (request -> token iterator),
    honoring arrival times (``time_scale`` stretches/compresses them).
    Each in-flight request holds one client thread — the streaming
    consumption model real callers have. ``on_error`` classifies
    exceptions: return "shed"/"deadline"/"error" (default heuristics
    inspect the type name)."""
    from ray_tpu.serve.admission import (DeadlineExceededError,
                                         RequestShedError)

    stats = ReplayStats()
    lock = threading.Lock()
    sem = threading.Semaphore(max_clients)
    t0 = time.monotonic()

    def classify(req: Request, e: BaseException) -> str:
        if on_error is not None:
            return on_error(req, e)
        if isinstance(e, RequestShedError):
            return "shed"
        if isinstance(e, DeadlineExceededError):
            return "deadline"
        # serve wraps engine-side errors (TaskError/RuntimeError): the
        # class name survives only in str() (remote traceback), and the
        # MESSAGE prefixes are part of the admission API ("request shed
        # (<reason>)", "request deadline") — match either so shed/
        # deadline accounting survives every wrapper
        s = repr(e) + " " + str(e)
        if "RequestShedError" in s or "request shed (" in s:
            return "shed"
        if "DeadlineExceededError" in s or "request deadline" in s:
            return "deadline"
        return "error"

    def client(req: Request) -> None:
        try:
            t_submit = time.monotonic()
            first = None
            last = t_submit
            n = 0
            try:
                for tok in stream_fn(req):
                    now = time.monotonic()
                    if first is None:
                        first = now - t_submit
                    else:
                        with lock:
                            stats.tpot.append(now - last)
                    last = now
                    n += 1
            except BaseException as e:  # noqa: BLE001 - classified below
                kind = classify(req, e)
                with lock:
                    if kind == "shed":
                        stats.shed += 1
                    elif kind == "deadline":
                        stats.deadline += 1
                    else:
                        stats.errors += 1
                    stats.tokens += n
                return
            with lock:
                stats.completed += 1
                stats.tokens += n
                if first is not None:
                    stats.ttft.append(first)
        finally:
            sem.release()

    threads: List[threading.Thread] = []
    for req in trace:
        target = t0 + req.arrival_s * time_scale
        delay = target - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        sem.acquire()
        stats.started += 1
        th = threading.Thread(target=client, args=(req,), daemon=True)
        th.start()
        threads.append(th)
    for th in threads:
        th.join(timeout=300)
    stats.wall_s = time.monotonic() - t0
    return stats


# ---------------------------------------------------------------------------
# drivers: in-process engine (bench A/B) and deployed application
# ---------------------------------------------------------------------------

class EngineRunner:
    """Minimal deployment-shaped wrapper over one in-process LLMEngine:
    a stepper thread plus a queue-backed token stream per request — the
    same-container A/B vehicle (no actor boot noise in the numbers)."""

    def __init__(self, engine):
        self.engine = engine
        self._stop = False
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while not self._stop:
            if not self.engine.step():
                time.sleep(0.001)

    def stream(self, req: Request,
               deadline_s: Optional[float] = None) -> Iterable[int]:
        import queue as _q

        q: "_q.Queue[Any]" = _q.Queue()
        r = self.engine.submit(req.prompt, req.max_new, q.put_nowait,
                               deadline_s=deadline_s)
        try:
            while True:
                tok = q.get(timeout=120.0)
                if tok is None:
                    return
                if isinstance(tok, BaseException):
                    raise tok
                yield tok
        finally:
            self.engine.cancel(r)

    def close(self):
        self._stop = True
        self._thread.join(timeout=5)


def run_engine_ab(scale: str = "quick", paged: bool = True,
                  prefix_cache: bool = True, seed: int = 0,
                  model: str = "llama-debug",
                  time_scale: float = 0.0) -> Dict[str, Any]:
    """One replay against one in-process engine; returns the scorecard
    plus engine KV/prefix state. ``time_scale=0`` = closed-loop (submit
    as fast as clients free up) — the throughput-capability measurement;
    > 0 replays real arrival times."""
    from ray_tpu.serve.llm import LLMEngine
    from ray_tpu.util.tpu_info import honor_jax_platform_env

    honor_jax_platform_env()
    cfg = _scale_trace(scale, seed)
    engine = LLMEngine(model, max_slots=8, max_len=256, seed=seed,
                       paged=paged, prefix_cache=prefix_cache,
                       block_size=16, prefill_chunk=8)
    runner = EngineRunner(engine)
    try:
        trace = gen_trace(cfg)
        # warm the compile out of the measurement
        list(runner.stream(Request(0.0, 0, trace[0].prompt[:8], 2)))
        stats = replay(runner.stream, trace, time_scale=time_scale)
    finally:
        runner.close()
    out = stats.summary()
    kv = engine.kv_state()
    if "prefix" in kv:
        p = kv["prefix"]
        lookups = max(p["hits"] + p["misses"], 1)
        out["prefix_hit_rate"] = round(p["hits"] / lookups, 4)
        out["prefix_hit_tokens"] = p["hit_tokens"]
    out["paged"] = paged
    return out


def _scale_trace(scale: str, seed: int) -> TraceConfig:
    if scale == "quick":          # 2-vCPU CI tier
        return TraceConfig(n_requests=48, n_tenants=3,
                           shared_prefix_tokens=48, max_new_tokens=8,
                           burst_rps=200.0, seed=seed)
    if scale == "medium":
        return TraceConfig(n_requests=2_000, n_tenants=8,
                           shared_prefix_tokens=96, max_new_tokens=32,
                           burst_rps=500.0, seed=seed)
    # full: the millions-of-requests envelope (real hardware only)
    return TraceConfig(n_requests=1_000_000, n_tenants=64,
                       shared_prefix_tokens=128, max_new_tokens=64,
                       burst_rps=2_000.0, seed=seed)


def run_serve_replay(scale: str, replicas: int, paged: bool,
                     seed: int = 0, deadline_s: Optional[float] = None,
                     slo: Optional[dict] = None) -> Dict[str, Any]:
    """Deploy a multi-replica LLMDeployment and replay through the real
    handle/routing path (load-aware picker, admission, streaming)."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve import LLMDeployment

    ray_tpu.init(ignore_reinit_error=True)
    app = serve.deployment(
        LLMDeployment, num_replicas=replicas,
        ray_actor_options={"max_concurrency": 16, "num_cpus": 0},
    ).bind("llama-debug", max_slots=8, max_len=256, seed=seed,
           paged=paged, block_size=16, prefill_chunk=8, slo=slo)
    handle = serve.run(app, name="llm_replay")
    stream_handle = handle.options(stream=True)

    def stream(req: Request):
        return stream_handle.remote(req.prompt, req.max_new,
                                    deadline_s=deadline_s)

    trace = gen_trace(_scale_trace(scale, seed))
    # warm every replica's compile before timing
    for _ in range(replicas * 2):
        list(stream_handle.remote(trace[0].prompt[:8], 2))
    stats = replay(stream, trace, time_scale=0.0)
    out = stats.summary()
    # aggregate replica-side KV/prefix state — enumerate the replicas
    # directly (a ROUTED probe per replica can land on the same one
    # twice and double-count its hits)
    handle._refresh(force=True)
    kv = [ray_tpu.get(r.handle_request.remote("kv_state", (), {}),
                      timeout=60) for r in handle._replicas]
    hits = sum(k.get("prefix", {}).get("hits", 0) for k in kv)
    lookups = sum(k.get("prefix", {}).get("hits", 0)
                  + k.get("prefix", {}).get("misses", 0) for k in kv)
    out["prefix_hit_rate"] = round(hits / max(lookups, 1), 4)
    out["replicas"] = replicas
    out["paged"] = paged
    serve.delete("LLMDeployment")
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--scale", default="quick",
                   choices=("quick", "medium", "full"))
    p.add_argument("--serve", action="store_true",
                   help="drive a deployed multi-replica app (default: "
                        "in-process engine A/B)")
    p.add_argument("--replicas", type=int, default=2)
    p.add_argument("--dense", action="store_true",
                   help="dense baseline instead of paged")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)
    if args.serve:
        out = run_serve_replay(args.scale, args.replicas,
                               paged=not args.dense, seed=args.seed)
    else:
        out = run_engine_ab(args.scale, paged=not args.dense,
                            seed=args.seed)
    print(json.dumps({"metric": "serve_replay", **out}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
