"""Flagship benchmark: LLM train-step throughput + MFU on the local device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The metric is model FLOPs utilization (MFU) of a Llama-family training step
(fwd+bwd+adamw, bf16 matmuls, remat on) — the BASELINE.json north-star
contract ("Llama-3-8B ≥45% MFU on v5e-256"); ``vs_baseline`` is MFU/0.45.
On CPU (no TPU attached) the same harness runs a tiny config so the number
is still produced, just not meaningful as MFU.
"""

from __future__ import annotations

import json
import time


def main() -> None:
    from ray_tpu.util.tpu_info import honor_jax_platform_env

    honor_jax_platform_env()
    import jax
    import numpy as np
    import optax

    from ray_tpu import models
    from ray_tpu.parallel import MeshConfig
    from ray_tpu.train import TrainLoopHelper
    from ray_tpu.util.tpu_info import is_tpu_backend, peak_flops_per_chip

    on_tpu = is_tpu_backend()
    if on_tpu:
        # remat off: the 250M model's activations fit HBM, and remat would
        # burn ~1/3 extra FLOPs the 6N-based MFU accounting doesn't credit
        config = models.llama_250m().replace(remat=False)
        batch_size, seq = 16, 2048
        warmup, iters = 3, 10
    else:
        config = models.llama_debug()
        batch_size, seq = 4, 128
        warmup, iters = 2, 5

    n_dev = jax.device_count()
    helper = TrainLoopHelper.create(
        lambda: models.init_params(jax.random.PRNGKey(0), config),
        models.param_axes(config),
        lambda p, b: models.loss_and_metrics(p, b, config),
        optax.adamw(1e-4),
        mesh_config=MeshConfig(dp=1, fsdp=-1, tp=1, sp=1),
    )

    rng = np.random.default_rng(0)
    toks = rng.integers(0, config.vocab_size, size=(batch_size, seq + 1),
                        dtype=np.int32)
    batch = {"inputs": toks[:, :-1], "targets": toks[:, 1:]}

    for _ in range(warmup):
        metrics = helper.run_step(batch)
    jax.block_until_ready(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(iters):
        metrics = helper.run_step(batch)
    jax.block_until_ready(metrics["loss"])
    dt = (time.perf_counter() - t0) / iters

    tokens_per_step = batch_size * seq
    tokens_per_sec = tokens_per_step / dt
    # fwd+bwd ≈ 6N FLOPs/token + attention term 12*L*d*s (causal halves it)
    flops_token = config.flops_per_token() + (
        6 * config.n_layers * config.hdim * config.n_heads * seq)
    model_flops = flops_token * tokens_per_sec
    peak = peak_flops_per_chip() * n_dev if on_tpu else float("nan")
    mfu = model_flops / peak if on_tpu else 0.0

    result = {
        "metric": "llama_train_mfu" if on_tpu else "llama_train_tokens_per_sec_cpu",
        "value": round(mfu, 4) if on_tpu else round(tokens_per_sec, 1),
        "unit": "mfu" if on_tpu else "tokens/s",
        "vs_baseline": round(mfu / 0.45, 4) if on_tpu else 0.0,
        "detail": {
            "model": "llama-250m" if on_tpu else "llama-debug",
            "tokens_per_sec": round(tokens_per_sec, 1),
            "step_time_ms": round(dt * 1e3, 2),
            "devices": n_dev,
            "backend": jax.default_backend(),
            "loss": float(jax.device_get(metrics["loss"])),
            "core_microbench": _core_microbench(),
        },
    }
    print(json.dumps(result))


def _core_microbench() -> dict:
    """Core-runtime rates (reference microbenchmark analog:
    release/microbenchmark/run_microbenchmark.py — tasks/s, actor calls/s,
    put GB/s) measured on a throwaway local cluster."""
    import numpy as np

    import ray_tpu

    out = {}
    started = False
    try:
        ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
        started = True

        @ray_tpu.remote
        def noop():
            return None

        # warm the pool
        ray_tpu.get([noop.remote() for _ in range(20)])
        n = 300
        t0 = time.perf_counter()
        ray_tpu.get([noop.remote() for _ in range(n)])
        out["tasks_per_s"] = round(n / (time.perf_counter() - t0), 1)

        @ray_tpu.remote
        class A:
            def f(self):
                return None

        a = A.remote()
        ray_tpu.get(a.f.remote())
        t0 = time.perf_counter()
        ray_tpu.get([a.f.remote() for _ in range(n)])
        out["actor_calls_per_s"] = round(n / (time.perf_counter() - t0), 1)

        # numpy payload rides the zero-copy out-of-band buffer path (the
        # realistic ML case; raw bytes pickle in-band)
        arr = np.random.default_rng(0).standard_normal(1 << 20)  # 8 MiB
        nbytes = arr.nbytes
        t0 = time.perf_counter()
        refs = [ray_tpu.put(arr) for _ in range(16)]
        dt = time.perf_counter() - t0
        out["put_gb_per_s"] = round(16 * nbytes / dt / 1e9, 2)
        t0 = time.perf_counter()
        for r in refs:
            ray_tpu.get(r)
        out["get_gb_per_s"] = round(
            16 * nbytes / (time.perf_counter() - t0) / 1e9, 2)
    except Exception as e:  # bench must never fail on the micro side
        out["error"] = str(e)
    finally:
        if started:
            try:
                ray_tpu.shutdown()
            except Exception:
                pass
    return out


if __name__ == "__main__":
    main()
