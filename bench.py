"""Flagship benchmark: LLM train-step throughput + MFU on the local device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "detail"}.

The metric is model FLOPs utilization (MFU) of a Llama-family training step
(fwd+bwd+adamw, bf16 matmuls) — the BASELINE.json north-star contract
("Llama-3-8B >=45% MFU on v5e-256"); ``vs_baseline`` is MFU/0.45. On CPU
(no TPU attached) the same harness runs a tiny config so the number is
still produced, just not meaningful as MFU.

Resilience contract (the round-1 bench died on a transient backend-init
failure and emitted nothing): the parent process never touches jax. The
TPU train-step measurement runs in a child process with a timeout and
retry-with-backoff around transient ``UNAVAILABLE`` backend claims; the
Pallas flash kernel is preflighted on the real chip and the model falls
back to the blockwise XLA kernel if Mosaic rejects it; whatever happens,
exactly one valid JSON line is printed.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from ray_tpu import config as _rtpu_config  # jax-free

_CHILD_TIMEOUT_S = float(_rtpu_config.get("bench_child_timeout"))
_RETRIES = int(_rtpu_config.get("bench_retries"))
_TOTAL_BUDGET_S = float(_rtpu_config.get("bench_budget"))
_BACKOFFS = (5, 15, 30)


# ---------------------------------------------------------------------------
# Parent: orchestrates, never imports jax, always prints one JSON line.
# ---------------------------------------------------------------------------

def main() -> None:
    detail: dict = {}
    errors: list = []
    t_start = time.monotonic()

    # Emit a parseable JSON line even when an outer harness TERMs us
    # mid-run (a silently killed bench is how round 1 lost its numbers).
    import signal

    def _on_term(signum, frame):
        print(json.dumps({
            "metric": "llama_train_mfu", "value": 0.0, "unit": "mfu",
            "vs_baseline": 0.0,
            "error": f"bench terminated by signal {signum} after "
                     f"{time.monotonic() - t_start:.0f}s",
            "detail": detail,
        }), flush=True)
        os._exit(1)

    signal.signal(signal.SIGTERM, _on_term)

    # Core-runtime microbench first: pure ray_tpu (no jax on the driver
    # path), so it survives any TPU trouble — round 1 lost these numbers
    # because the TPU crash happened first.
    detail["core_microbench"] = _core_microbench()
    # Native-driver A/B (r14): same-container off/on comparison of the
    # GIL-free control-pipe engine + parallel data plane — the only
    # numbers that mean anything on container-throttled boxes.
    detail["native_pipe"] = _native_pipe_ab()
    # Streaming-shuffle bench (r6): out-of-core sort throughput + peak
    # RSS, so exchange regressions (a stage starting to materialize)
    # show up in the BENCH trajectory.
    detail["data_shuffle"] = _data_shuffle_bench()
    # Serving-tier A/Bs (r14): dense vs paged+prefix-reuse on the
    # shared-prefix replay trace, and round-robin vs load-aware routing
    # under skewed load — same-container, CPU-pinned.
    detail["serve_llm"] = _serve_llm_bench()
    # Disaggregated prefill/decode A/B (r16): colocated vs split pools
    # with KV-block shipping on the mixed long-prefill + steady-decode
    # trace — same-container, CPU-pinned.
    detail["serve_disagg"] = _serve_disagg_bench()
    # Multi-model serving plane A/Bs (r17): N models multiplexed through
    # arena-paged registries vs the Zipf-hottest subset statically
    # dedicated on the same fleet weight budget, and speculative on/off
    # on the greedy decode path — same-container, CPU-pinned.
    detail["serve_multiplex"] = _serve_multiplex_bench()

    # Cheap pre-gate (VERDICT r3 #4): a ~25s device probe decides whether
    # the axon tunnel is alive BEFORE burning a 420s train-child timeout.
    # When the tunnel is down the whole bench finishes in ~2 min, so the
    # driver can re-run it cheaply whenever the tunnel revives. An
    # intentionally CPU-pinned run (CLAUDE.md local invocation) skips the
    # probe — and the error field — entirely.
    tpu_wanted = os.environ.get("JAX_PLATFORMS", "") != "cpu"
    if tpu_wanted:
        probe = _probe_tpu()
        if not probe["ok"]:
            _kill_stale_chip_holders(errors)  # stale holder, not an outage?
            probe = _probe_tpu()
        detail["tpu_probe"] = probe["detail"]
        if not probe["ok"]:
            errors.append(f"tpu probe: {probe['detail']}")
            tpu_wanted = False
            # The round-long watcher (ray_tpu bench --watch) may have
            # caught the chip during a tunnel-up window earlier in the
            # round; a cached real-TPU measurement beats a CPU fallback.
            cached = _load_watch_cache()
            if cached is not None:
                try:
                    result = dict(cached["bench"])
                    result.setdefault("detail", {})
                    result["detail"]["core_microbench"] = detail["core_microbench"]
                    result["detail"]["tpu_cache"] = {
                        "measured_at": cached.get("iso"),
                        "age_s": round(time.time()
                                       - float(cached.get("ts", 0))),
                        "note": "tunnel down at report time; value "
                                "measured on-chip by the round-long "
                                "bench watcher",
                    }
                    if cached.get("numerics"):
                        result["detail"]["pallas_numerics_on_chip"] = \
                            cached["numerics"]
                    sweep = _load_sweep_results()
                    if sweep:
                        # on-chip sweeps run after the cached bench may
                        # have measured improved configs the bench has
                        # since adopted; report them alongside (clearly
                        # labeled) rather than silently understating
                        result["detail"]["onchip_sweep_after_cache"] = sweep
                    print(json.dumps(result))
                    return
                except Exception as e:
                    # malformed cache must not break the one-JSON-line
                    # contract; fall through to the CPU path
                    errors.append(f"watch cache unusable: {e}")

    child = None
    for attempt in range(_RETRIES if tpu_wanted else 0):
        child = _run_train_child(
            timeout=max(60.0, min(_CHILD_TIMEOUT_S,
                                  _TOTAL_BUDGET_S - (time.monotonic() - t_start))))
        if child.get("ok"):
            break
        errors.append(f"attempt {attempt + 1}: {child.get('error', 'unknown')}")
        if child.get("timeout"):
            break  # a hung compile won't improve with retries
        if time.monotonic() - t_start > _TOTAL_BUDGET_S:
            errors.append("total bench budget exhausted")
            break
        if "UNAVAILABLE" in child.get("error", ""):
            # only after an observed failed claim: a stale bench child from
            # a previous timed-out run may still be pinning the chip
            _kill_stale_chip_holders(errors)
        if attempt < _RETRIES - 1:
            time.sleep(_BACKOFFS[min(attempt, len(_BACKOFFS) - 1)])

    if child and child.get("ok"):
        result = child["result"]
        result.setdefault("detail", {}).update(detail)
        if errors:
            result["detail"]["transient_errors"] = errors
        print(json.dumps(result))
        return

    # TPU path unrecoverable (or never wanted): one CPU-pinned attempt so
    # the harness still exercises the full train step. The error field is
    # set only when a TPU run was intended and failed.
    cpu = _run_train_child(force_cpu=True)
    if cpu.get("ok"):
        result = cpu["result"]
        result.setdefault("detail", {}).update(detail)
        if errors:
            result["detail"]["tpu_errors"] = errors
            result["error"] = ("tpu backend unavailable; "
                               "cpu fallback numbers")
        print(json.dumps(result))
        return

    errors.append(f"cpu fallback: {cpu.get('error', 'unknown')}")
    mb = detail.get("core_microbench", {})
    print(json.dumps({
        "metric": "llama_train_mfu",
        "value": 0.0,
        "unit": "mfu",
        "vs_baseline": 0.0,
        "error": "; ".join(errors)[-2000:],
        "detail": detail,
        "core_tasks_per_s": mb.get("tasks_per_s"),
    }))


def _best_sweep_rec():
    """Best measured on-chip sweep record (R5 preferred, R4 fallback), or
    None. R5 records carry the full cfg dict so the bench can adopt the
    winning (remat, batch, loss_chunk, tiles) configuration."""
    best = None
    for fname in ("MFU_SWEEP_R5_RESULTS.jsonl", "MFU_SWEEP_R4_RESULTS.jsonl"):
        try:
            path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "experiments", fname)
            with open(path) as f:
                for line in f:
                    # a malformed record (hand edit, schema drift) must
                    # never break the one-JSON-line bench contract
                    try:
                        rec = json.loads(line)
                        if (rec.get("ok")
                                and isinstance(rec.get("mfu"), (int, float))
                                and isinstance(rec.get("cfg", {}), dict)
                                and (best is None or rec["mfu"] > best["mfu"])):
                            best = rec
                    except Exception:
                        continue
        except OSError:
            continue
        if best:
            break  # R5 measurements supersede R4's
    return best


def _load_sweep_results():
    """Summary of the best on-chip sweep result for the report, or None."""
    best = _best_sweep_rec()
    if best:
        return {"best_config": best.get("name"), "mfu": best.get("mfu"),
                "tokens_per_sec": best.get("tokens_per_sec"),
                "note": ("measured on-chip by experiments/mfu_sweep.py "
                         "during a tunnel window; bench adopts this "
                         "config when it has a full cfg record")}
    return None


def _load_watch_cache():
    """Last good on-chip result cached by ray_tpu.util.tpu_watch, or None."""
    try:
        from ray_tpu.util.tpu_watch import load_cache

        return load_cache()
    except Exception:
        return None


def _probe_tpu(timeout: float = 25.0) -> dict:
    """Child-process device query: is the axon tunnel answering? Cold
    runtime start is ~7s when healthy; a hang past ``timeout`` means the
    tunnel is down (it can be down for hours — see CLAUDE.md)."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; jax.config.update('jax_platforms', 'axon'); "
             "print('NDEV', len(jax.devices()))"],
            capture_output=True, text=True, timeout=timeout,
            env=dict(os.environ))
    except subprocess.TimeoutExpired:
        return {"ok": False,
                "detail": f"device query hung {timeout:.0f}s (tunnel down)"}
    except Exception as e:  # pragma: no cover - spawn failure
        return {"ok": False, "detail": f"probe spawn failed: {e}"}
    ok = proc.returncode == 0 and "NDEV" in proc.stdout
    tail = (proc.stdout if ok else (proc.stderr or proc.stdout))[-300:]
    return {"ok": ok, "detail": tail.strip()}


def _run_train_child(force_cpu: bool = False,
                     timeout: float = _CHILD_TIMEOUT_S) -> dict:
    """Run the train-step measurement in a subprocess; parse its JSON tail."""
    env = dict(os.environ)
    # the ENFORCED timeout (may be smaller than the knob when the total
    # bench budget is nearly spent) — the child's decode-budget guard
    # must respect this one, not the knob
    env["RTPU_BENCH_CHILD_ENFORCED_TIMEOUT_S"] = str(timeout)
    if force_cpu:
        env["JAX_PLATFORMS"] = "cpu"
    else:
        # Adopt the best measured sweep config's env (attention tile
        # knobs, XLA_FLAGS) — these must be set before the child's
        # interpreter starts because the axon sitecustomize imports jax
        # into every process.
        try:
            best = _best_sweep_rec()
            for k, v in ((best or {}).get("cfg", {}).get("env") or {}).items():
                # merge composite flag vars rather than clobber the caller's
                env[k] = (env[k] + " " + str(v)
                          if k == "XLA_FLAGS" and k in env else str(v))
        except Exception:
            pass  # a bad sweep record must not block the bench
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--train-step"],
            capture_output=True, text=True, timeout=timeout, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return {"ok": False, "timeout": True,
                "error": f"train-step child timed out after {timeout}s"}
    except Exception as e:  # pragma: no cover - spawn failure
        return {"ok": False, "error": f"spawn failed: {e}"}
    for line in reversed(proc.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return {"ok": True, "result": json.loads(line)}
            except json.JSONDecodeError:
                continue
    tail = (proc.stderr or proc.stdout or "")[-1500:]
    return {"ok": False, "error": f"rc={proc.returncode}: {tail}"}


def _kill_stale_chip_holders(errors: list) -> None:
    """Best-effort: SIGKILL stale *bench* python processes holding a TPU fd.

    A previous bench run killed by an outer timeout can leave a child
    pinning the chip, which makes every subsequent backend init fail
    UNAVAILABLE. Called only after an observed UNAVAILABLE claim, and only
    targets processes whose cmdline looks like a bench/python train child —
    never system daemons, brokers, or unrelated VFIO users.
    """
    import signal

    me = os.getpid()
    ancestors = set()
    pid = me
    try:
        for _ in range(10):
            with open(f"/proc/{pid}/status") as f:
                ppid_line = next(l for l in f if l.startswith("PPid:"))
            pid = int(ppid_line.split()[1])
            if pid <= 1:
                break
            ancestors.add(pid)
    except Exception:
        pass
    try:
        for pid_dir in os.listdir("/proc"):
            if not pid_dir.isdigit():
                continue
            pid = int(pid_dir)
            if pid == me or pid in ancestors:
                continue
            try:
                with open(f"/proc/{pid}/cmdline", "rb") as f:
                    cmdline = f.read().replace(b"\0", b" ").decode(
                        "utf-8", "replace")
            except OSError:
                continue
            if "python" not in cmdline or "bench.py" not in cmdline:
                continue
            fd_dir = f"/proc/{pid}/fd"
            try:
                for fd in os.listdir(fd_dir):
                    target = os.readlink(os.path.join(fd_dir, fd))
                    if target.startswith("/dev/accel") or target.startswith("/dev/vfio"):
                        os.kill(pid, signal.SIGKILL)
                        errors.append(f"killed stale chip holder pid={pid}")
                        break
            except (PermissionError, FileNotFoundError, OSError):
                continue
    except Exception:
        pass


# ---------------------------------------------------------------------------
# Child: jax lives here. Prints one JSON line on success, raises otherwise.
# ---------------------------------------------------------------------------

def train_step_child() -> None:
    child_t0 = time.monotonic()
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from ray_tpu.util.tpu_info import honor_jax_platform_env

    honor_jax_platform_env()
    import jax

    backend = _claim_backend(jax)
    on_tpu = backend in ("tpu", "axon")

    attn_impl, attn_note = "xla", "cpu backend: blockwise XLA attention"
    if on_tpu:
        attn_impl, attn_note = _preflight_pallas(jax)
    from ray_tpu.ops.attention import set_default_attention_impl

    set_default_attention_impl(attn_impl)

    rl_rate = _rl_learner_bench(jax)

    result = None
    last_exc = None
    batch_sizes = (16, 8, 4)
    if on_tpu:
        best = _best_sweep_rec()
        b = (best or {}).get("cfg", {}).get("batch")
        if isinstance(b, int) and b > 0:
            # OOM fallback must only SHRINK: the adopted config may also
            # carry a longer seq, so a larger batch would OOM harder
            batch_sizes = (b,) + tuple(x for x in (16, 8, 4) if x < b)
    for batch_size in batch_sizes:
        try:
            result = _measure(jax, on_tpu, batch_size)
            break
        except Exception as e:
            last_exc = e
            msg = str(e)
            if on_tpu and attn_impl == "pallas" and "RESOURCE_EXHAUSTED" not in msg:
                # Mosaic can reject the kernel only inside the full scan
                # program even when the standalone preflight compiled.
                set_default_attention_impl("xla")
                attn_impl = "xla"
                attn_note = (f"pallas failed in full program ({e}); "
                             f"blockwise XLA fallback")
                try:
                    result = _measure(jax, on_tpu, batch_size)
                    break
                except Exception as e2:
                    last_exc = e2
                    msg = str(e2)
            if "RESOURCE_EXHAUSTED" not in msg and "Allocation" not in msg:
                raise
            # HBM OOM: shrink the batch and retry (activation residuals
            # scale linearly with batch even under remat)
    if result is None:
        raise last_exc
    result["detail"]["attention_impl"] = attn_note
    result["detail"]["rl_learner_grad_steps_per_s"] = rl_rate
    result["detail"]["rl_forward_exploration"] = _rl_forward_bench(jax)
    # decode bench LAST and only with >=120s of the ENFORCED child
    # timeout left (the parent may enforce less than the knob when the
    # total bench budget is nearly spent): a slow decode compile must
    # never time the child out and lose the train MFU measured during a
    # scarce tunnel window
    enforced = float(os.environ.get("RTPU_BENCH_CHILD_ENFORCED_TIMEOUT_S",
                                    _CHILD_TIMEOUT_S))
    budget_left = enforced - (time.monotonic() - child_t0)
    if budget_left >= 120.0:
        result["detail"]["decode"] = _decode_bench(jax, on_tpu)
    else:
        result["detail"]["decode"] = {"skipped":
                                      f"{budget_left:.0f}s budget left"}
    # device-plane section: the compiled-program registry this child
    # populated (compile wall times, cost-analysis flops, HBM
    # watermarks) — tpu_watch lifts it into BENCH_TPU_LAST_GOOD.json so
    # the last good window's compile/cost table survives tunnel-down
    # rounds. Signature histories are dropped (they bloat the one-line
    # JSON without adding to the table).
    try:
        from ray_tpu.util import device_plane as _dp

        snap = _dp.snapshot(census=False) or {}
        rows = []
        for r in snap.get("programs") or ():
            r.pop("sigs", None)
            rows.append(r)
        dp_detail = {"programs": rows}
        if snap.get("hbm"):
            dp_detail["hbm"] = snap["hbm"]
        result["detail"]["device_plane"] = dp_detail
    except Exception:
        pass
    print(json.dumps(result))


def _decode_bench(jax, on_tpu: bool) -> dict:
    """Serving-path throughput: greedy decode tokens/s on the flagship
    model (batch 8, prefill 128, 128 new tokens; the CPU fallback uses
    the same tiny config as the CPU train path — a 250M decode takes
    minutes on 2 vCPUs). generate()'s decode loop is one lax.scan
    program, so the timing is a single dispatch with a final
    data-dependent read (tunnel-safe)."""
    try:
        import numpy as np

        from ray_tpu import models

        name = "llama-250m" if on_tpu else "llama-debug"
        config = models.get_config(name).replace(remat=False)
        params = models.init_params(jax.random.PRNGKey(0), config)
        prompt = jax.numpy.asarray(np.random.default_rng(0).integers(
            0, config.vocab_size, (8, 128), dtype=np.int32))
        new = 128

        def run():
            out = models.generate(params, prompt, config,
                                  max_new_tokens=new)
            # data-dependent read spanning the whole scan
            return int(jax.device_get(out[:, -1].astype(
                jax.numpy.int32).sum()))

        t0 = time.perf_counter()
        run()  # compile + warm
        compile_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        run()
        dt = time.perf_counter() - t0
        return {"tokens_per_sec": round(8 * new / dt, 1),
                "model": name, "batch": 8, "new_tokens": new,
                "prefill": 128, "compile_warm_s": round(compile_s, 1)}
    except Exception as e:
        return {"error": str(e)[:200]}


def _rl_learner_bench(jax) -> float:
    """PPO learner grad-steps/s on this device (north-star: learner
    throughput vs the reference's 8xA100 DDP learner)."""
    try:
        import numpy as np

        from ray_tpu.rllib.ppo import PPOLearner

        spec = {"observation_dim": 84, "action_dim": 6, "discrete": True,
                "hidden": (256, 256)}
        learner = PPOLearner(spec, {"num_devices": 1}, seed=0)
        rng = np.random.default_rng(0)
        n = 4096
        batch = {
            "obs": rng.standard_normal((n, 84)).astype(np.float32),
            "actions": rng.integers(0, 6, n),
            "action_logp": np.full(n, -1.79, np.float32),
            "vf_preds": rng.standard_normal(n).astype(np.float32),
            "advantages": rng.standard_normal(n).astype(np.float32),
            "value_targets": rng.standard_normal(n).astype(np.float32),
        }
        # warm with the SAME (epochs, minibatch) signature as the timed
        # call: update() scans the whole epoch×minibatch plan as one
        # program, so a different num_epochs is a different program
        epochs = 4
        learner.update(batch, minibatch_size=512, num_epochs=epochs)
        t0 = time.perf_counter()
        learner.update(batch, minibatch_size=512, num_epochs=epochs)
        dt = time.perf_counter() - t0
        steps = epochs * (n // 512)
        return round(steps / dt, 1)
    except Exception:
        return 0.0


def _rl_forward_bench(jax) -> dict:
    """RLModule forward_exploration: jit vs eager speedup — the analog
    of the reference's one checked-in ML-library number (torch.compile
    forward_exploration speedups, rllib/benchmarks/torch_compile:
    +33.9% CPU ... +156.7% A100). jax.jit is the jax-native compile."""
    try:
        if jax.default_backend() != "cpu":
            # On the tunneled axon backend the eager arm is dominated by
            # per-op tunnel round-trips (the speedup would measure RTT,
            # not compile benefit) and 50 eager dispatches could eat the
            # train child's budget during a scarce tunnel window. The
            # reference's primary comparator is its CPU number anyway.
            return {"skipped": "CPU-only micro-bench (eager arm is "
                               "dispatch-RTT-dominated off-CPU)"}
        import numpy as np

        from ray_tpu.rllib.rl_module import RLModuleSpec

        spec = RLModuleSpec(observation_dim=84, action_dim=6,
                            discrete=True, hidden=(256, 256))
        module = spec.build()
        params = module.init(jax.random.PRNGKey(0))
        obs0 = jax.numpy.asarray(
            np.random.default_rng(0).standard_normal(
                (32, 84)).astype(np.float32))
        rng = jax.random.PRNGKey(1)

        jitted = jax.jit(module.forward_exploration)

        def timed(fn, n=50):
            jax.block_until_ready(fn(params, obs0, rng))  # warm
            obs = obs0
            t0 = time.perf_counter()
            for _ in range(n):
                out = fn(params, obs, rng)
                # chain: next input depends on this output, so the final
                # device_get provably spans all n calls (CLAUDE.md
                # timing rule)
                obs = obs0 + 0.0 * out["vf_preds"][:, None]
            float(jax.device_get(out["vf_preds"].sum()))
            return (time.perf_counter() - t0) / n

        eager_s = timed(module.forward_exploration)
        jit_s = timed(jitted)
        return {"eager_ms": round(eager_s * 1e3, 3),
                "jit_ms": round(jit_s * 1e3, 3),
                "speedup_pct": round((eager_s / jit_s - 1) * 100, 1)}
    except Exception:
        return {}


def _claim_backend(jax, retries: int = 4) -> str:
    """jax.default_backend() with retry — the axon tunnel can be transiently
    unclaimable (UNAVAILABLE) right after another process released it."""
    last = None
    for attempt in range(retries):
        try:
            return jax.default_backend()
        except Exception as e:  # RuntimeError/JaxRuntimeError wrapping UNAVAILABLE
            last = e
            try:
                import jax.extend.backend

                jax.extend.backend.clear_backends()
            except Exception:
                pass
            time.sleep(2 * (attempt + 1))
    raise RuntimeError(f"backend init failed after {retries} attempts: {last}")


def _preflight_pallas(jax):
    """Compile the flash kernel fwd+bwd on the real chip before trusting it
    (the training step differentiates it, so forward-only is not enough)."""
    import jax.numpy as jnp

    from ray_tpu.ops.attention import flash_attention

    try:
        q = jnp.ones((1, 1024, 4, 128), jnp.bfloat16)
        k = jnp.ones((1, 1024, 2, 128), jnp.bfloat16)

        def loss(q, k, v):
            return flash_attention(q, k, v, causal=True,
                                   impl="pallas").astype(jnp.float32).sum()

        out, grads = jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, k)
        jax.block_until_ready(grads)
        return "pallas", "pallas flash kernel (fwd+bwd preflight ok)"
    except Exception as e:
        return "xla", f"pallas preflight failed ({type(e).__name__}: {e}); blockwise XLA fallback"


def _measure(jax, on_tpu: bool, batch_size: int = 16) -> dict:
    import numpy as np
    import optax

    from ray_tpu import models
    from ray_tpu.parallel import MeshConfig
    from ray_tpu.train import TrainLoopHelper
    from ray_tpu.util.tpu_info import peak_flops_per_chip

    if on_tpu:
        # remat ON (full-layer): the round-4 on-chip sweep
        # (experiments/mfu_sweep.py) measured remat+batch16+pallas at
        # 0.203 MFU vs 0.143 for the old no-remat path (which OOMed past
        # batch 4 — 31G of scanned-layer residuals vs 15.75G HBM). The 6N
        # MFU accounting stays conservative: remat's recompute FLOPs are
        # real work the credit ignores. When the R5 sweep has measured a
        # better config, adopt its remat policy / loss_chunk / seq.
        config = models.llama_250m()
        seq = 2048
        iters = 10
        best = _best_sweep_rec()
        if best and best.get("cfg"):
            cfg = best["cfg"]
            config = config.replace(
                remat=cfg.get("remat", True),
                remat_policy=cfg.get("policy", "full"),
                loss_chunk=cfg.get("loss_chunk", 0))
            seq = cfg.get("seq", 2048)
    else:
        config = models.llama_debug()
        batch_size, seq = 4, 128
        iters = 5

    n_dev = jax.device_count()
    helper = TrainLoopHelper.create(
        lambda: models.init_params(jax.random.PRNGKey(0), config),
        models.param_axes(config),
        lambda p, b: models.loss_and_metrics(p, b, config),
        optax.adamw(1e-4),
        mesh_config=MeshConfig(dp=1, fsdp=-1, tp=1, sp=1),
    )

    rng = np.random.default_rng(0)
    toks = rng.integers(0, config.vocab_size, size=(batch_size, seq + 1),
                        dtype=np.int32)
    batch = {"inputs": toks[:, :-1], "targets": toks[:, 1:]}

    # Timing discipline for the tunneled axon backend: block_until_ready
    # acks long before execution completes (round-1 measured an impossible
    # ~70x-peak "MFU" with it), so the wait must be a VALUE TRANSFER
    # (device_get) of something data-dependent on the work. The inner loop
    # is a single scanned n-step program (TrainLoopHelper.run_steps) — the
    # idiomatic TPU loop: one dispatch + one device_get per n steps, and
    # the returned loss chains through every step's params, so the get
    # provably spans all n steps.
    # one warmup call compiles the scanned program AND warms the chip;
    # the single-step program is never timed, so never compile it
    metrics = helper.run_steps(batch, iters)
    float(jax.device_get(metrics["loss"]))

    t0 = time.perf_counter()
    metrics = helper.run_steps(batch, iters)
    loss = float(jax.device_get(metrics["loss"]))
    dt = (time.perf_counter() - t0) / iters

    tokens_per_step = batch_size * seq
    tokens_per_sec = tokens_per_step / dt
    # fwd+bwd ~= 6N FLOPs/token + attention term 12*L*d*s (causal halves it)
    flops_token = config.flops_per_token() + (
        6 * config.n_layers * config.hdim * config.n_heads * seq)
    model_flops = flops_token * tokens_per_sec
    peak = peak_flops_per_chip() * n_dev if on_tpu else float("nan")
    mfu = model_flops / peak if on_tpu else 0.0

    # self-reporting perf trajectory: the measured step lands in the
    # train-telemetry metrics (HBM gauges included on-chip) and its
    # snapshot rides the bench JSON
    try:
        from ray_tpu.train import telemetry

        telemetry.record_step(dt, tokens=tokens_per_step,
                              mfu=(mfu if on_tpu else None),
                              loss=loss, steps=iters,
                              program="train::run_steps")
        tele = telemetry.snapshot()
    except Exception:
        tele = None

    # cost-model attribution (device plane): achieved FLOP/s from the
    # registered run_steps program's XLA cost analysis. Detail only —
    # the headline keeps the hand 6N formula for cross-round
    # comparability (cost-analysis flops count remat recompute, so this
    # reads hardware utilization, not model MFU).
    cost_model = None
    try:
        from ray_tpu.util import device_plane as _dp

        fps = _dp.program_flops_per_step("train::run_steps")
        if fps:
            achieved = fps / dt
            cost_model = {
                "flops_per_step": fps,
                "achieved_flops_per_s": achieved,
                "mfu_cost_model": (round(achieved / peak, 4)
                                   if on_tpu else None),
            }
    except Exception:
        pass

    return {
        "metric": "llama_train_mfu" if on_tpu else "llama_train_tokens_per_sec_cpu",
        "value": round(mfu, 4) if on_tpu else round(tokens_per_sec, 1),
        "unit": "mfu" if on_tpu else "tokens/s",
        "vs_baseline": round(mfu / 0.45, 4) if on_tpu else 0.0,
        "detail": {
            "model": "llama-250m" if on_tpu else "llama-debug",
            "batch_size": batch_size,
            "tokens_per_sec": round(tokens_per_sec, 1),
            "step_time_ms": round(dt * 1e3, 2),
            "devices": n_dev,
            "backend": jax.default_backend(),
            "device_kind": getattr(jax.devices()[0], "device_kind", "unknown"),
            "timing_mode": ("scanned n-step program, single dependent "
                            "device_get (tunnel-safe)"),
            "loss": loss,
            "telemetry": tele,
            "cost_model": cost_model,
        },
    }


# ---------------------------------------------------------------------------
# Core-runtime microbenchmark (reference analog:
# release/microbenchmark/run_microbenchmark.py — tasks/s, actor calls/s,
# put GB/s) on a throwaway local cluster. jax-free.
# ---------------------------------------------------------------------------

def _data_shuffle_bench() -> dict:
    """Out-of-core sort through the streaming exchange, scaled for a
    2-vCPU box: 24 MB of (key, payload) rows sorted under an 8 MB spill
    threshold. Reports rows/s (best-of-3 per the CLAUDE.md noise rule —
    capability, not average-under-load) and the peak per-process RSS
    growth over the run (max across driver + workers): a materializing
    regression shows up as peak_rss_mb jumping toward the dataset size."""
    import threading

    import numpy as np

    out = {}
    n_blocks, rows_per = 12, 125_000  # 12 x 125k x 16 B = 24 MB
    overrides = {
        "RTPU_STORE_CAPACITY": str(4 << 20),
        "RTPU_SPILL_THRESHOLD": str(8 << 20),
        "RTPU_DATA_EXCHANGE_RUN_BYTES": str(2 << 20),
        "RTPU_STORE_PREFAULT_BYTES": "0",
    }
    saved = {k: os.environ.get(k) for k in overrides}
    os.environ.update(overrides)
    started = False
    try:
        import ray_tpu
        from ray_tpu.core.runtime import _get_runtime
        from ray_tpu.data.dataset import Dataset

        ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
        started = True

        def gen():
            rng = np.random.default_rng(0)
            for i in range(n_blocks):
                yield {"key": rng.integers(0, 1 << 40, size=rows_per),
                       "pay": np.full(rows_per, float(i))}

        def _vmrss_kb(pid):
            try:
                with open(f"/proc/{pid}/status") as f:
                    for line in f:
                        if line.startswith("VmRSS:"):
                            return int(line.split()[1])
            except OSError:
                pass
            return None

        stop = threading.Event()
        rss = {}  # pid -> [base, peak]
        spill_peak = [0]

        def sample():
            while not stop.wait(0.05):
                pids = [os.getpid()]
                try:
                    pids += [ws.proc.pid for ws in
                             list(_get_runtime().workers.values())]
                except Exception:
                    pass
                for pid in pids:
                    kb = _vmrss_kb(pid)
                    if kb is None:
                        continue
                    ent = rss.setdefault(pid, [kb, kb])
                    ent[1] = max(ent[1], kb)
                try:
                    spill_peak[0] = max(
                        spill_peak[0],
                        ray_tpu.object_store_memory()["spilled_bytes"])
                except Exception:
                    pass

        def trial():
            t0 = time.perf_counter()
            rows = 0
            last = None
            for ref in Dataset(gen).sort(
                    "key", num_blocks=8).iter_block_refs():
                block = ray_tpu.get(ref)
                keys = block.get("key")
                if keys is None or not len(keys):
                    continue
                assert np.all(keys[1:] >= keys[:-1])
                assert last is None or keys[0] >= last
                last = keys[-1]
                rows += len(keys)
                ray_tpu.free(ref)
            assert rows == n_blocks * rows_per
            return rows / (time.perf_counter() - t0)

        trial()  # warm: pool spawn + first-exchange fixed costs
        t = threading.Thread(target=sample, daemon=True)
        t.start()
        try:
            out["sort_rows_per_s"] = round(max(trial() for _ in range(3)))
        finally:
            stop.set()
            t.join(timeout=5)
        out["peak_rss_mb"] = round(max(
            (peak - base) for base, peak in rss.values()) / 1024, 1)
        out["dataset_mb"] = round(n_blocks * rows_per * 16 / 1e6, 1)
        out["peak_spilled_mb"] = round(spill_peak[0] / 1e6, 1)
    except Exception as e:  # the bench must never die on the data side
        out["error"] = str(e)
    finally:
        if started:
            try:
                ray_tpu.shutdown()
            except Exception:
                pass
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return out


def _native_pipe_ab() -> dict:
    """Same-container off/on A/B of the native driver (r14 tentpole):
    tasks/s, single- and multi-client shapes with pipe messages/task and
    driver-CPU/task (the r13 431 µs baseline comparator), and put GB/s
    against a PRE-WARMED arena (CLAUDE.md: the cold-arena zero-fill is a
    one-time cost that would otherwise drown the copy-path signal).
    Each mode boots a fresh runtime; everything else is identical."""
    import resource as _resource

    import numpy as np

    import ray_tpu

    def _pipe_msg_total():
        from ray_tpu.util.metrics import registry_records as _rr

        total = 0.0
        for rec in _rr():
            if rec["name"] != "rtpu_pipe_messages_total":
                continue
            for _k, v in rec["samples"]:
                total += v if not isinstance(v, tuple) else v[2]
        return total

    def one_mode(on: bool) -> dict:
        out: dict = {}
        os.environ["RTPU_NATIVE_PIPE"] = "1" if on else "0"
        started = False
        try:
            ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
            started = True

            @ray_tpu.remote
            def noop():
                return None

            for _ in range(3):
                ray_tpu.get([noop.remote() for _ in range(60)])
            if on:
                from ray_tpu.core.runtime import _get_runtime

                # dialed-back workers only: a replenishment spawn
                # mid-boot legitimately has no engine yet
                live = [ws for ws in _get_runtime().workers.values()
                        if ws.status != "dead" and ws.conn is not None]
                out["engine_attached"] = bool(live) and all(
                    ws.npipe is not None for ws in live)

            n = 600

            def tasks_trial():
                t0 = time.perf_counter()
                ray_tpu.get([noop.remote() for _ in range(n)])
                return n / (time.perf_counter() - t0)

            out["tasks_per_s"] = round(
                max(tasks_trial() for _ in range(3)), 1)

            @ray_tpu.remote
            class BatchClient:
                def small_value_batch(self, k):
                    ray_tpu.get([noop.remote() for _ in range(k)])
                    return k

            clients = [BatchClient.remote() for _ in range(2)]
            ray_tpu.get([c.small_value_batch.remote(10) for c in clients])
            best = None
            for _ in range(3):
                ru0 = _resource.getrusage(_resource.RUSAGE_SELF)
                cpu0 = ru0.ru_utime + ru0.ru_stime
                m0 = _pipe_msg_total()
                t0 = time.perf_counter()
                ray_tpu.get(
                    [c.small_value_batch.remote(250) for c in clients])
                wall = time.perf_counter() - t0
                ru1 = _resource.getrusage(_resource.RUSAGE_SELF)
                rec = {
                    "rate_per_s": round(500.0 / wall, 1),
                    "driver_cpu_us_per_task": round(
                        (ru1.ru_utime + ru1.ru_stime - cpu0) / 500.0
                        * 1e6, 1),
                    "pipe_msgs_per_task": round(
                        (_pipe_msg_total() - m0) / 500.0, 2),
                }
                if best is None or rec["rate_per_s"] > best["rate_per_s"]:
                    best = rec
            out["multi_client"] = best

            # put bandwidth, warm arena first (one throwaway burst of the
            # same footprint pre-faults the extents the timed burst hits)
            arr = np.random.default_rng(0).standard_normal(1 << 20)
            for _ in range(16):
                ray_tpu.put(arr)
            rates = []
            for _ in range(3):
                t0 = time.perf_counter()
                refs = [ray_tpu.put(arr) for _ in range(16)]
                rates.append(
                    16 * arr.nbytes / (time.perf_counter() - t0) / 1e9)
                del refs
            out["put_gb_per_s_warm"] = round(max(rates), 2)

            @ray_tpu.remote
            def do_put(nbytes, times):
                data = np.zeros(nbytes // 8)
                for _ in range(times):
                    ray_tpu.put(data)
                return times * nbytes

            ray_tpu.get(do_put.remote(1 << 16, 1))

            def multi_put_trial(nbytes=8 << 20, times=4, m=2):
                t0 = time.perf_counter()
                ray_tpu.get([do_put.remote(nbytes, times)
                             for _ in range(m)])
                return m * times * nbytes / (time.perf_counter() - t0) / 1e9

            out["multi_client_put_gb_per_s"] = round(
                max(multi_put_trial() for _ in range(3)), 2)
            for c in clients:
                ray_tpu.kill(c)
        except Exception as e:  # the bench must never die on the A/B
            out["error"] = str(e)[:300]
        finally:
            if started:
                try:
                    ray_tpu.shutdown()
                except Exception:
                    pass
        return out

    saved = os.environ.get("RTPU_NATIVE_PIPE")
    try:
        result = {"off": one_mode(False), "on": one_mode(True)}
    finally:
        if saved is None:
            os.environ.pop("RTPU_NATIVE_PIPE", None)
        else:
            os.environ["RTPU_NATIVE_PIPE"] = saved
    try:
        on, off = result["on"], result["off"]
        result["summary"] = {
            "tasks_ratio_on_off": round(
                on["tasks_per_s"] / off["tasks_per_s"], 3),
            "multi_vs_single_client_on": round(
                on["multi_client"]["rate_per_s"] / on["tasks_per_s"], 3),
            "driver_cpu_delta_us": round(
                on["multi_client"]["driver_cpu_us_per_task"]
                - off["multi_client"]["driver_cpu_us_per_task"], 1),
        }
    except Exception:
        pass
    return result


def _serve_llm_bench() -> dict:
    """Serving-tier same-container A/Bs (ISSUE 12). Two comparisons:

    - ``paged_ab``: the shared-prefix replay trace through one
      in-process engine, dense vs paged+prefix-reuse — tokens/s, TTFT
      p99, prefix hit rate (best-of-3 per the CLAUDE.md noise rule).
      Runs in a CPU-pinned child so the bench driver never touches jax
      (or the chip) for a control-plane measurement.
    - ``routing_ab``: round-robin vs load-aware routing on a 2-replica
      sleepy deployment with one replica pre-loaded — wall time to
      drain a burst (the router's job is to keep the burst off the busy
      replica)."""
    import subprocess

    out: dict = {}
    env = dict(os.environ, JAX_PLATFORMS="cpu", RTPU_TRACING="0")
    here = os.path.dirname(os.path.abspath(__file__))

    def engine_trial(paged: bool):
        code = ("from experiments.serve_replay import run_engine_ab; "
                "import json; print(json.dumps(run_engine_ab('quick', "
                f"paged={paged})))")
        p = subprocess.run([sys.executable, "-c", code], text=True,
                           capture_output=True, timeout=300, env=env,
                           cwd=here)
        if p.returncode != 0:
            raise RuntimeError(p.stderr[-500:])
        return json.loads(p.stdout.strip().splitlines()[-1])

    try:
        for label, paged in (("paged", True), ("dense", False)):
            trials = [engine_trial(paged) for _ in range(3)]
            # best-of-3 PER METRIC (capability, not one lucky run):
            # max throughput, min tail latency — the CLAUDE.md noise rule
            best = {
                "tokens_per_s": max(t["tokens_per_s"] for t in trials),
                "ttft_p99_s": min(t["ttft_p99_s"] for t in trials),
                "tpot_p99_s": min(t["tpot_p99_s"] for t in trials),
            }
            if "prefix_hit_rate" in trials[0]:
                best["prefix_hit_rate"] = max(
                    t["prefix_hit_rate"] for t in trials)
            out.setdefault("paged_ab", {})[label] = best
        pab = out.get("paged_ab", {})
        if "paged" in pab and "dense" in pab:
            out["paged_ab"]["speedup"] = round(
                pab["paged"]["tokens_per_s"]
                / max(pab["dense"]["tokens_per_s"], 1e-9), 2)
    except Exception as e:
        out["paged_ab_error"] = str(e)[-300:]

    try:
        out["routing_ab"] = _serve_routing_ab()
    except Exception as e:
        out["routing_ab_error"] = str(e)[-300:]
    return out


def _serve_disagg_bench() -> dict:
    """Colocated-vs-disaggregated same-container A/B (ISSUE 13): the
    mixed long-prefill + steady-decode replay trace through DEPLOYED
    two-replica apps — colocated routes whole requests load-aware over
    two mixed replicas; disaggregated dedicates one replica to prefill
    and one to decode with KV blocks shipped over the DeviceChannel
    path between them. Deployed (separate replica processes), not
    in-process: two engines sharing one jax CPU device serialize their
    steps on the device queue, which hands prefill interference right
    back to decode and erases the architecture delta. Same hardware,
    same trace, best-of-3 per metric (the CLAUDE.md noise rule); each
    trial is a CPU-pinned child so the bench driver never touches jax.
    The contract: disagg shows lower TPOT p99 at >= comparable
    tokens/s (long prefills stop stealing decode step-time)."""
    import subprocess

    out: dict = {}
    env = dict(os.environ, JAX_PLATFORMS="cpu", RTPU_TRACING="0")
    here = os.path.dirname(os.path.abspath(__file__))

    def trial(disagg: bool):
        code = ("from experiments.serve_replay import run_serve_replay; "
                "import json; print(json.dumps(run_serve_replay("
                f"'quick', replicas=2, paged=True, disagg={disagg}, "
                "mixed=True, max_clients=8)))")
        p = subprocess.run([sys.executable, "-c", code], text=True,
                           capture_output=True, timeout=600, env=env,
                           cwd=here)
        if p.returncode != 0:
            raise RuntimeError(p.stderr[-500:])
        return json.loads(p.stdout.strip().splitlines()[-1])

    try:
        leaks = 0
        for label, disagg in (("disagg", True), ("colocated", False)):
            trials = [trial(disagg) for _ in range(3)]
            leaks += sum(t.get("kv_leaks", 0) for t in trials)
            # best-of-3 PER METRIC: max throughput, min tail latency
            out[label] = {
                "tokens_per_s": max(t["tokens_per_s"] for t in trials),
                "ttft_p99_s": min(t["ttft_p99_s"] for t in trials),
                "tpot_p50_s": min(t["tpot_p50_s"] for t in trials),
                "tpot_p99_s": min(t["tpot_p99_s"] for t in trials),
            }
        out["kv_leaks"] = leaks
        if "disagg" in out and "colocated" in out:
            out["tpot_p99_speedup"] = round(
                out["colocated"]["tpot_p99_s"]
                / max(out["disagg"]["tpot_p99_s"], 1e-9), 2)
            out["tokens_ratio"] = round(
                out["disagg"]["tokens_per_s"]
                / max(out["colocated"]["tokens_per_s"], 1e-9), 2)
    except Exception as e:
        out["error"] = str(e)[-300:]
    return out


def _serve_multiplex_bench() -> dict:
    """Multi-model serving-plane same-container A/Bs (ISSUE 16).

    Two comparisons, best-of-3 per metric (the CLAUDE.md noise rule):
    - consolidation: the same 8-model Zipf trace and the same fleet
      weight budget (2 replicas x 2 model-slots) spent two ways —
      EVERY model served through multiplexed registries that page
      weights on demand, vs the Zipf-hottest 4 statically dedicated
      (requests for unhosted models hard-shed). Open-loop arrivals, so
      a shed is lost tokens at unchanged wall time.
    - speculative: ngram-draft speculative decoding on vs off on the
      greedy gpt2-debug path (token-exact by construction; the parity
      tests hold the guarantee, this holds the speedup).
    Each trial is a CPU-pinned child so the bench driver never touches
    jax. Rounds interleave all four arms and the wall budget stops
    WHOLE rounds, so both sides of each A/B keep equal trial counts."""
    import subprocess

    out: dict = {}
    env = dict(os.environ, JAX_PLATFORMS="cpu", RTPU_TRACING="0")
    here = os.path.dirname(os.path.abspath(__file__))

    def trial(call: str) -> dict:
        code = ("from experiments.serve_replay import run_multiplex_ab, "
                "run_spec_ab; import json; "
                f"print(json.dumps({call}))")
        p = subprocess.run([sys.executable, "-c", code], text=True,
                           capture_output=True, timeout=600, env=env,
                           cwd=here)
        if p.returncode != 0:
            raise RuntimeError(p.stderr[-500:])
        return json.loads(p.stdout.strip().splitlines()[-1])

    arms = {
        "multiplex": "run_multiplex_ab('quick', dedicated=False)",
        "dedicated": "run_multiplex_ab('quick', dedicated=True)",
        "spec_on": "run_spec_ab('quick', spec=True)",
        "spec_off": "run_spec_ab('quick', spec=False)",
    }
    trials: dict = {k: [] for k in arms}
    budget_s = float(os.environ.get("RTPU_BENCH_MUX_BUDGET_S", "900"))
    t0 = time.monotonic()
    try:
        for _ in range(3):
            for label, call in arms.items():
                trials[label].append(trial(call))
            if time.monotonic() - t0 > budget_s * 2 / 3:
                break  # whole rounds only: arms stay comparable
        for label, ts in trials.items():
            best = max(ts, key=lambda t: t["tokens_per_s"])
            row = {"tokens_per_s": max(t["tokens_per_s"] for t in ts),
                   "ttft_p99_s": min(t["ttft_p99_s"] for t in ts),
                   "trials": len(ts)}
            # counters come from the best-throughput trial: they are a
            # property of one coherent run, not a cross-run extremum
            for k in ("shed", "swaps_in", "swaps_out", "engines",
                      "hosted_models", "spec_accept_rate"):
                if k in best:
                    row[k] = best[k]
            out[label] = row
        out["consolidation_tokens_ratio"] = round(
            out["multiplex"]["tokens_per_s"]
            / max(out["dedicated"]["tokens_per_s"], 1e-9), 2)
        # lazy paging proof: the multiplex arm must have churned, not
        # just held everything resident
        out["paging_proven"] = bool(
            out["multiplex"].get("swaps_out", 0) > 0)
        out["spec_speedup"] = round(
            out["spec_on"]["tokens_per_s"]
            / max(out["spec_off"]["tokens_per_s"], 1e-9), 2)
    except Exception as e:
        out["error"] = str(e)[-300:]
    return out


def _serve_routing_ab() -> dict:
    import ray_tpu
    from ray_tpu import serve

    res: dict = {}
    started = False
    saved = os.environ.get("RTPU_SERVE_ROUTING")
    try:
        ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
        started = True

        @serve.deployment(num_replicas=2, max_ongoing_requests=16)
        def sleepy(dt=0.05):
            import time as _t

            _t.sleep(dt)
            return 1

        handle = serve.run(sleepy.bind(), name="bench_routing")
        for _ in range(6):  # warm both replicas + their workers
            handle.remote(0.001).result(timeout_s=60)

        def trial(mode: str) -> float:
            os.environ["RTPU_SERVE_ROUTING"] = mode
            # skew: a DEEP queue of short calls pinned onto replica 0 —
            # the depth signal p2c routes on (burst depth stays below
            # it, so the load-aware picker keeps the whole burst on
            # replica 1; round-robin parks half of it behind the queue)
            skew = [handle._replicas[0].handle_request.remote(
                "__call__", (0.2,), {}) for _ in range(12)]
            time.sleep(0.15)  # let queue depths surface in the runtime
            t0 = time.perf_counter()
            rs = [handle.remote(0.05) for _ in range(10)]
            for r in rs:
                r.result(timeout_s=60)
            wall = time.perf_counter() - t0
            ray_tpu.get(skew, timeout=60)
            return wall

        # alternate modes so background noise hits both equally
        walls = {"rr": [], "p2c": []}
        for _ in range(2):
            for mode in ("rr", "p2c"):
                walls[mode].append(trial(mode))
        for mode, ws in walls.items():
            res[mode] = {"burst_wall_best_s": round(min(ws), 3),
                         "burst_wall_all_s": [round(w, 3) for w in ws]}
        res["speedup"] = round(
            res["rr"]["burst_wall_best_s"]
            / max(res["p2c"]["burst_wall_best_s"], 1e-9), 2)
        serve.delete("sleepy")
    finally:
        if saved is None:
            os.environ.pop("RTPU_SERVE_ROUTING", None)
        else:
            os.environ["RTPU_SERVE_ROUTING"] = saved
        if started:
            try:
                serve.shutdown()
                ray_tpu.shutdown()
            except Exception:
                pass
    return res


_DP_AB_CODE = r"""
import json, time
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from ray_tpu.util import device_plane as dp

f = dp.registered_jit(lambda x: x + 1.0,
                      name="bench::overhead_probe", component="bench")
x = jnp.zeros((8,))
f(x)  # compile once, outside both windows

def trial(n=2000):
    t0 = time.perf_counter()
    for _ in range(n):
        f(x)
    return n / (time.perf_counter() - t0)

best = lambda k, fn: max(fn() for _ in range(k))
dp.disable_device_plane()
off = best(3, trial)
dp.enable_device_plane()
on = best(3, trial)
print(json.dumps({"jit_calls_per_s_off": round(off, 1),
                  "jit_calls_per_s_on": round(on, 1),
                  "on_off_ratio": round(on / off, 3) if off else None}))
"""


def _device_plane_overhead_ab() -> dict:
    """Registered-jit wrapper cost, armed vs disarmed, in a CPU-pinned
    child (best-of-3 each per the CLAUDE.md noise rule)."""
    import subprocess

    try:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        here = os.path.dirname(os.path.abspath(__file__))
        p = subprocess.run([sys.executable, "-c", _DP_AB_CODE],
                           text=True, capture_output=True, timeout=300,
                           env=env, cwd=here)
        if p.returncode != 0:
            return {"error": p.stderr[-300:]}
        return json.loads(p.stdout.strip().splitlines()[-1])
    except Exception as e:
        return {"error": str(e)}


def _core_microbench() -> dict:
    import numpy as np

    import ray_tpu

    out = {}
    started = False
    try:
        ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
        started = True

        @ray_tpu.remote
        def noop():
            return None

        # warm the pool to steady state: the first bursts grow the pool to
        # its 4-worker cap (zygote spawns land mid-burst otherwise) — the
        # reference microbenchmark also times warm workers only
        for _ in range(3):
            ray_tpu.get([noop.remote() for _ in range(60)])

        def best_of(k, fn, ndigits=1):
            # Throughput CAPABILITY on a noisy 2-vCPU box: background
            # daemons (the round-long TPU watcher's 25s probe child) can
            # steal a core mid-sample and halve a short loop's rate; the
            # max over k short trials reads through that transient noise.
            return round(max(fn() for _ in range(k)), ndigits)

        n = 600

        def tasks_trial():
            t0 = time.perf_counter()
            ray_tpu.get([noop.remote() for _ in range(n)])
            return n / (time.perf_counter() - t0)

        out["tasks_per_s"] = best_of(3, tasks_trial)

        # tracing on/off A/B on the SAME warm process tree (ISSUE 7
        # bench guard): the off number re-measures right before the on
        # number so a disabled-path cost regression (span() must stay
        # one dict get) or an enabled-path span-cost blowup both surface
        # in the JSON line. enable_tracing reaches the live workers over
        # their control pipes — no respawn between the two sides.
        try:
            from ray_tpu.util import tracing as _tracing

            t_off = best_of(3, tasks_trial)
            try:
                _tracing.enable_tracing()
                t_on = best_of(3, tasks_trial)
            finally:
                # a failed on-trial must not leave tracing armed for the
                # rest of the microbench (it would corrupt every later
                # number this guard exists to protect)
                _tracing.disable_tracing()
            out["tracing_overhead"] = {
                "tasks_per_s_off": t_off,
                "tasks_per_s_on": t_on,
                "on_off_ratio": round(t_on / t_off, 3) if t_off else None,
            }
        except Exception as e:
            out["tracing_overhead"] = {"error": str(e)}

        # profiling on/off A/B on the SAME warm process tree (ISSUE 9
        # bench guard, same contract as tracing_overhead): the disarmed
        # number re-measures right before the armed one so a
        # disarmed-path regression (profiling_enabled() must stay one
        # dict get) or an armed-at-default-Hz sampler cost > the 10%
        # acceptance bound both surface in the JSON line.
        try:
            from ray_tpu.util import profiling as _profiling

            p_off = best_of(3, tasks_trial)
            try:
                _profiling.enable_profiling()
                p_on = best_of(3, tasks_trial)
            finally:
                _profiling.disable_profiling()
            out["profiling_overhead"] = {
                "tasks_per_s_off": p_off,
                "tasks_per_s_on": p_on,
                "on_off_ratio": round(p_on / p_off, 3) if p_off else None,
                "hz": _profiling._hz(),
            }
        except Exception as e:
            out["profiling_overhead"] = {"error": str(e)}

        # events on/off A/B on the SAME warm process tree (ISSUE 18
        # bench guard). The plane defaults ON, so unlike tracing/
        # profiling the interesting direction is inverted: measure
        # disarmed first, then re-arm (the shipped default) and measure
        # again — the on/off ratio bounds what worker_spawn/worker_death
        # recording costs on the task hot path. MUST end re-armed:
        # leaving events off would silently disarm the default-on plane
        # for the rest of the microbench.
        try:
            from ray_tpu.util import events as _events

            _events.disable_events()
            try:
                e_off = best_of(3, tasks_trial)
            finally:
                _events.enable_events()
            e_on = best_of(3, tasks_trial)
            out["events_overhead"] = {
                "tasks_per_s_off": e_off,
                "tasks_per_s_on": e_on,
                "on_off_ratio": round(e_on / e_off, 3) if e_off else None,
            }
        except Exception as e:
            out["events_overhead"] = {"error": str(e)}

        # device plane on/off A/B (ISSUE 19 bench guard): the hot path
        # is NOT tasks/s — it's the RegisteredFunction.__call__ wrapper
        # around an already-compiled jit (one enabled-check + one
        # cache-size probe + one counted call when armed), so the A/B
        # drives a tiny jitted fn where wrapper cost is the dominant
        # term. Runs in a CPU-pinned child: the bench driver never
        # touches jax (tunnel-down axon device queries hang). Same
        # child measures disarmed-then-armed for a same-tree ratio.
        out["device_plane_overhead"] = _device_plane_overhead_ab()

        @ray_tpu.remote
        class A:
            def f(self):
                return None

        a = A.remote()
        ray_tpu.get(a.f.remote())

        # reference 1_1_actor_calls_sync: one call at a time
        def sync_trial():
            t0 = time.perf_counter()
            for _ in range(150):
                ray_tpu.get(a.f.remote())
            return 150 / (time.perf_counter() - t0)

        out["actor_calls_sync_per_s"] = best_of(3, sync_trial)

        # reference 1_1_actor_calls_async: burst submit, then drain
        def async_trial():
            t0 = time.perf_counter()
            ray_tpu.get([a.f.remote() for _ in range(n)])
            return n / (time.perf_counter() - t0)

        out["actor_calls_per_s"] = best_of(3, async_trial)

        # reference placement_group_create/removal rate
        from ray_tpu.util.placement_group import (placement_group,
                                                  remove_placement_group)

        def pg_trial():
            t0 = time.perf_counter()
            for _ in range(50):
                pg = placement_group([{"CPU": 1}], strategy="PACK")
                remove_placement_group(pg)
            return 50 / (time.perf_counter() - t0)

        out["pg_create_remove_per_s"] = best_of(3, pg_trial)

        # -- multi-client + n:n benches (reference ray_perf.py:189,232,146:
        # "multi client" = WORKER-side clients submitting core-API calls
        # from inside actors/tasks, not extra driver processes) -----------

        @ray_tpu.remote
        class BatchClient:
            def small_value_batch(self, n):
                ray_tpu.get([noop.remote() for _ in range(n)])
                return n

        clients = [BatchClient.remote() for _ in range(2)]
        ray_tpu.get([c.small_value_batch.remote(10) for c in clients])  # warm

        def multi_task_trial(n=250):
            t0 = time.perf_counter()
            ray_tpu.get([c.small_value_batch.remote(n) for c in clients])
            return len(clients) * n / (time.perf_counter() - t0)

        out["multi_client_tasks_async_per_s"] = best_of(3, multi_task_trial)

        # multi-client control-plane cost detail (ISSUE 10 acceptance:
        # pipe messages/task <= 2.5 from 5.0 after coalescing): frames +
        # driver CPU around one multi-client run
        try:
            import resource as _resource

            from ray_tpu.util.metrics import registry_records as _rr

            def _pipe_msg_total():
                total = 0.0
                for rec in _rr():
                    if rec["name"] != "rtpu_pipe_messages_total":
                        continue
                    for _k, v in rec["samples"]:
                        total += v if not isinstance(v, tuple) else v[2]
                return total

            _ru0 = _resource.getrusage(_resource.RUSAGE_SELF)
            _cpu0 = _ru0.ru_utime + _ru0.ru_stime
            _m0 = _pipe_msg_total()
            _t0 = time.perf_counter()
            ray_tpu.get([c.small_value_batch.remote(250) for c in clients])
            _wall = time.perf_counter() - _t0
            _ru1 = _resource.getrusage(_resource.RUSAGE_SELF)
            _n = 500.0
            out["multi_client_detail"] = {
                "pipe_msgs_per_task": round(
                    (_pipe_msg_total() - _m0) / _n, 2),
                "driver_cpu_us_per_task": round(
                    (_ru1.ru_utime + _ru1.ru_stime - _cpu0) / _n * 1e6, 1),
                "rate_per_s": round(_n / _wall, 1),
            }
        except Exception as e:
            out["multi_client_detail"] = {"error": str(e)}

        # -- compiled execution plane (ISSUE 10): same-container A/B of a
        # 2-stage actor pipeline — compiled-DAG pipelined invocations vs
        # the equivalent per-call actor-call chain loop ------------------
        try:
            from ray_tpu.dag import InputNode

            @ray_tpu.remote
            class Stage:
                def __init__(self, k):
                    self.k = k

                def apply(self, x):
                    return x + self.k

            s1, s2 = Stage.remote(1), Stage.remote(100)
            ray_tpu.get([s1.apply.remote(0), s2.apply.remote(0)])  # warm

            def chain_trial(n=200):
                t0 = time.perf_counter()
                for i in range(n):
                    ray_tpu.get(s2.apply.remote(s1.apply.remote(i)))
                return n / (time.perf_counter() - t0)

            chain_rate = best_of(3, chain_trial)

            with InputNode() as inp:
                dag = s2.apply.bind(s1.apply.bind(inp))
            compiled = dag.experimental_compile(max_in_flight=8)
            assert compiled.execute(0).get(timeout=60) == 101  # warm

            def compiled_trial(n=2000):
                t0 = time.perf_counter()
                # execute() self-backpressures at max_in_flight, draining
                # completed results into their futures — full pipelining
                futs = [compiled.execute(i, timeout=120)
                        for i in range(n)]
                vals = [f.get(timeout=120) for f in futs]
                assert vals[-1] == n - 1 + 101
                return n / (time.perf_counter() - t0)

            compiled_rate = best_of(3, compiled_trial)
            compiled.teardown()
            ray_tpu.kill(s1)
            ray_tpu.kill(s2)
            out["compiled_dag"] = {
                "compiled_pipelined_per_s": compiled_rate,
                "actor_chain_per_s": chain_rate,
                "speedup": (round(compiled_rate / chain_rate, 1)
                            if chain_rate else None),
            }
        except Exception as e:
            out["compiled_dag"] = {"error": str(e)}

        @ray_tpu.remote
        def nn_work(actors, n):
            ray_tpu.get([actors[i % len(actors)].f.remote()
                         for i in range(n)])
            return n

        nn_actors = [A.options(num_cpus=0).remote() for _ in range(2)]
        ray_tpu.get([x.f.remote() for x in nn_actors])
        ray_tpu.get(nn_work.remote(nn_actors, 10))  # warm

        def nn_trial(m=2, n=150):
            t0 = time.perf_counter()
            ray_tpu.get([nn_work.remote(nn_actors, n) for _ in range(m)])
            return m * n / (time.perf_counter() - t0)

        out["n_n_actor_calls_async_per_s"] = best_of(3, nn_trial)

        @ray_tpu.remote
        def do_put(nbytes, times):
            data = np.zeros(nbytes // 8)
            for _ in range(times):
                ray_tpu.put(data)
            return times * nbytes

        ray_tpu.get(do_put.remote(1 << 16, 1))  # warm

        def multi_put_trial(nbytes=8 << 20, times=4, m=2):
            t0 = time.perf_counter()
            ray_tpu.get([do_put.remote(nbytes, times) for _ in range(m)])
            return m * times * nbytes / (time.perf_counter() - t0) / 1e9

        out["multi_client_put_gb_per_s"] = best_of(3, multi_put_trial,
                                                   ndigits=2)
        for x in nn_actors + clients:
            ray_tpu.kill(x)

        # numpy payload rides the zero-copy out-of-band buffer path (the
        # realistic ML case; raw bytes pickle in-band)
        arr = np.random.default_rng(0).standard_normal(1 << 20)  # 8 MiB
        nbytes = arr.nbytes

        # each trial pairs a fresh put burst with a COLD first read of its
        # own refs, so best-of never selects a warm re-read rate
        put_rates, get_rates = [], []
        for _ in range(3):
            t0 = time.perf_counter()
            refs = [ray_tpu.put(arr) for _ in range(16)]
            put_rates.append(16 * nbytes / (time.perf_counter() - t0) / 1e9)
            t0 = time.perf_counter()
            for r in refs:
                ray_tpu.get(r)
            get_rates.append(16 * nbytes / (time.perf_counter() - t0) / 1e9)
        out["put_gb_per_s"] = round(max(put_rates), 2)
        out["get_gb_per_s"] = round(max(get_rates), 2)

        # scalability-envelope analogs (reference
        # release/benchmarks/single_node.json: 10k get / wait / many
        # actors), scaled to this box so the numbers are comparable
        # across rounds
        refs1k = [ray_tpu.put(i) for i in range(1000)]
        t0 = time.perf_counter()
        ready, _ = ray_tpu.wait(refs1k, num_returns=1000, timeout=120)
        out["wait_1k_refs_s"] = round(time.perf_counter() - t0, 3)
        refs10k = [ray_tpu.put(i) for i in range(10000)]
        t0 = time.perf_counter()
        vals = ray_tpu.get(refs10k)
        out["get_10k_s"] = round(time.perf_counter() - t0, 3)
        assert vals[9999] == 9999
        t0 = time.perf_counter()
        actors = [A.options(num_cpus=0).remote() for _ in range(16)]
        ray_tpu.get([x.f.remote() for x in actors])
        out["actors_launched_per_s"] = round(
            16 / (time.perf_counter() - t0), 2)
        for x in actors:
            ray_tpu.kill(x)

        # spawn->ready latency behind actors_launched (ISSUE 4: the
        # zygote histogram attributes launch rate to worker-boot
        # queueing, not scheduler overhead) + the hottest locks of the
        # whole microbench — near-zero waits mean the driver is
        # CPU-bound, not lock-bound
        try:
            from ray_tpu.util import contention
            from ray_tpu.util.metrics import registry_records

            for rec in registry_records():
                if rec["name"] == "rtpu_worker_spawn_seconds":
                    for key, (counts, s, n) in rec["samples"]:
                        if n:
                            out.setdefault("spawn_latency", {})[
                                dict(key).get("mode", "?")] = {
                                "n": n, "mean_s": round(s / n, 3)}
            out["contention_hot"] = contention.top_waits(3)
        except Exception:
            pass
    except Exception as e:  # bench must never fail on the micro side
        out["error"] = str(e)
    finally:
        if started:
            try:
                ray_tpu.shutdown()
            except Exception:
                pass
    return out


if __name__ == "__main__":
    if "--train-step" in sys.argv:
        train_step_child()
    else:
        main()
